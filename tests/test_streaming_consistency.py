"""Streaming/batch consistency properties: the engine's core guarantee.

For random update streams (interleaved adds, overwrites and deletions
across many commit epochs) pushed through representative pipelines, the
FINAL streaming state must equal recomputing the same pipeline over the
final batch input — differential dataflow's defining invariant (the
reference inherits it from differential arrangements; our epoch engine
must reproduce it through its retraction machinery).

Each pipeline runs twice per seed: once over the update stream (python
connector emitting per-epoch adds/removes), once over a static table of
the surviving rows; results are compared as sorted value tuples.
"""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from tests.utils import run_to_rows


class _StreamSource(pw.io.python.ConnectorSubject):
    """Replays scripted epochs of ('add'|'remove', key, row) events."""

    def __init__(self, epochs: list[list[tuple]], schema):
        super().__init__()
        self._epochs = epochs
        self._sch = schema

    def run(self) -> None:
        from pathway_tpu.internals import keys as K
        from pathway_tpu.io._connector import coerce_row

        for epoch in self._epochs:
            for kind, key, row in epoch:
                k = K.ref_scalar("strm", key)
                if kind == "add":
                    self._events.add(k, coerce_row(row, self._sch))
                else:
                    self._events.remove(k, coerce_row(row, self._sch))
            self.commit()


def _random_history(rng: random.Random, n_keys: int, n_epochs: int):
    """Scripted epochs + the surviving final rows. Keys are overwritten
    via remove+add (upsert) and sometimes deleted outright."""
    alive: dict[int, dict] = {}
    epochs: list[list[tuple]] = []
    for _ in range(n_epochs):
        epoch: list[tuple] = []
        for _ in range(rng.randrange(1, 6)):
            key = rng.randrange(n_keys)
            action = rng.random()
            if key in alive and action < 0.25:
                epoch.append(("remove", key, alive.pop(key)))
            elif key in alive and action < 0.55:
                new = {"k": key, "g": rng.choice("xyz"), "v": rng.randrange(50)}
                epoch.append(("remove", key, alive[key]))
                epoch.append(("add", key, new))
                alive[key] = new
            elif key not in alive:
                row = {"k": key, "g": rng.choice("xyz"), "v": rng.randrange(50)}
                epoch.append(("add", key, row))
                alive[key] = row
        if epoch:
            epochs.append(epoch)
    return epochs, list(alive.values())


def _schema():
    return pw.schema_from_types(k=int, g=str, v=int)


def _stream_table(epochs):
    src = _StreamSource(epochs, _schema())
    return pw.io.python.read(src, schema=_schema())


def _batch_table(rows):
    return pw.debug.table_from_rows(
        _schema(), [(r["k"], r["g"], r["v"]) for r in rows]
    )


def _run_both(build, epochs, final_rows):
    pw.G.clear()
    streamed = sorted(run_to_rows(build(_stream_table(epochs))))
    pw.G.clear()
    batch = sorted(run_to_rows(build(_batch_table(final_rows))))
    return streamed, batch


@pytest.mark.parametrize("seed", range(6))
def test_select_filter_consistency(seed):
    rng = random.Random(seed)
    epochs, final = _random_history(rng, n_keys=8, n_epochs=10)

    def build(t):
        out = t.select(t.k, t.g, doubled=t.v * 2, flag=t.v % 3 == 0)
        return out.filter(out.doubled > 10)

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(6))
def test_groupby_aggregates_consistency(seed):
    rng = random.Random(20 + seed)
    epochs, final = _random_history(rng, n_keys=10, n_epochs=12)

    def build(t):
        return t.groupby(t.g).reduce(
            t.g,
            n=pw.reducers.count(),
            s=pw.reducers.sum(t.v),
            mx=pw.reducers.max(t.v),
            mn=pw.reducers.min(t.v),
        )

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(6))
def test_join_consistency(seed):
    """Self-join via two independent streams sharing the key space."""
    rng = random.Random(40 + seed)
    epochs_a, final_a = _random_history(rng, n_keys=6, n_epochs=8)
    epochs_b, final_b = _random_history(rng, n_keys=6, n_epochs=8)

    def build_pair(a, b):
        j = a.join(b, a.k == b.k)
        return j.select(a.k, va=a.v, vb=b.v)

    pw.G.clear()
    streamed = sorted(
        run_to_rows(
            build_pair(_stream_table(epochs_a), _stream_table(epochs_b))
        )
    )
    pw.G.clear()
    batch = sorted(
        run_to_rows(build_pair(_batch_table(final_a), _batch_table(final_b)))
    )
    assert streamed == batch


@pytest.mark.parametrize("seed", range(4))
def test_groupby_then_join_consistency(seed):
    """Two-stage pipeline: aggregates joined back against the rows."""
    rng = random.Random(60 + seed)
    epochs, final = _random_history(rng, n_keys=8, n_epochs=10)

    def build(t):
        g = t.groupby(t.g).reduce(t.g, total=pw.reducers.sum(t.v))
        j = t.join(g, t.g == g.g)
        return j.select(t.k, t.v, pw.right.total)

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(4))
def test_distinct_count_consistency(seed):
    rng = random.Random(80 + seed)
    epochs, final = _random_history(rng, n_keys=12, n_epochs=10)

    def build(t):
        per_g = t.groupby(t.g, t.v).reduce(t.g, t.v)
        return per_g.groupby(per_g.g).reduce(
            per_g.g, distinct_vals=pw.reducers.count()
        )

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(4))
def test_argmax_and_tuple_reducers_consistency(seed):
    rng = random.Random(120 + seed)
    epochs, final = _random_history(rng, n_keys=9, n_epochs=9)
    if not final:
        pytest.skip("empty final state for this seed")

    def build(t):
        return t.groupby(t.g).reduce(
            t.g,
            best_k=pw.reducers.argmax(t.v, t.k),
            vals=pw.reducers.sorted_tuple(t.v),
        )

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


def test_full_retraction_leaves_empty_state():
    """Every added row eventually retracted: all downstream state must
    drain to empty, including aggregates."""
    rows = [{"k": i, "g": "x", "v": i} for i in range(5)]
    epochs = [[("add", i, rows[i]) for i in range(5)]]
    epochs.append([("remove", i, rows[i]) for i in range(5)])

    def build(t):
        return t.groupby(t.g).reduce(t.g, n=pw.reducers.count())

    streamed, batch = _run_both(build, epochs, [])
    assert streamed == [] and batch == []


# ---------------------------------------------------------------------------
# universe operations under streaming updates


def _pair_histories(seed: int):
    rng = random.Random(seed)
    ea, fa = _random_history(rng, n_keys=6, n_epochs=8)
    eb, fb = _random_history(rng, n_keys=6, n_epochs=8)
    return ea, fa, eb, fb


def _keyed_batch_table(rows):
    """The final rows in ONE epoch through the SAME keyed source — the
    universe ops key on row identity, so the batch side must share the
    stream's key function (ref_scalar('strm', k))."""
    epoch = [("add", r["k"], r) for r in rows]
    return _stream_table([epoch] if epoch else [])


def _run_both_pair(build_pair, ea, fa, eb, fb):
    pw.G.clear()
    streamed = sorted(
        run_to_rows(build_pair(_stream_table(ea), _stream_table(eb)))
    )
    pw.G.clear()
    batch = sorted(
        run_to_rows(
            build_pair(_keyed_batch_table(fa), _keyed_batch_table(fb))
        )
    )
    return streamed, batch


@pytest.mark.parametrize("seed", range(4))
def test_update_rows_consistency(seed):
    """update_rows: B's rows overwrite A's at equal keys; both tables
    stream independently."""
    ea, fa, eb, fb = _pair_histories(200 + seed)

    def build_pair(a, b):
        return a.update_rows(b).select(pw.this.k, pw.this.g, pw.this.v)

    streamed, batch = _run_both_pair(build_pair, ea, fa, eb, fb)
    assert streamed == batch, (ea, eb)


@pytest.mark.parametrize("seed", range(4))
def test_intersect_difference_restrict_consistency(seed):
    """Universe ops track membership changes on both sides. The two
    sources share the key space (ref_scalar('strm', key)), so equal keys
    collide across tables — exactly what these ops key on."""
    ea, fa, eb, fb = _pair_histories(300 + seed)

    def build_inter(a, b):
        return a.intersect(b).select(pw.this.k, pw.this.v)

    def build_diff(a, b):
        return a.difference(b).select(pw.this.k, pw.this.v)

    streamed, batch = _run_both_pair(build_inter, ea, fa, eb, fb)
    assert streamed == batch, "intersect diverged"
    streamed, batch = _run_both_pair(build_diff, ea, fa, eb, fb)
    assert streamed == batch, "difference diverged"


@pytest.mark.parametrize("seed", range(4))
def test_ix_and_having_consistency(seed):
    """Pointer indirection (ix / having) under churn: looked-up rows
    follow the target table's updates."""
    ea, fa, eb, fb = _pair_histories(400 + seed)

    def build_having(a, b):
        from pathway_tpu.internals import keys as K

        ptrs = a.select(p=pw.apply(lambda k: K.ref_scalar("strm", k), a.k))
        return b.having(ptrs.p).select(pw.this.k, pw.this.v)

    streamed, batch = _run_both_pair(build_having, ea, fa, eb, fb)
    assert streamed == batch, (ea, eb)


@pytest.mark.parametrize("seed", range(3))
def test_concat_reindex_consistency(seed):
    ea, fa, eb, fb = _pair_histories(500 + seed)

    def build_pair(a, b):
        u = a.concat_reindex(b)
        return u.groupby(u.g).reduce(u.g, n=pw.reducers.count(), s=pw.reducers.sum(u.v))

    streamed, batch = _run_both_pair(build_pair, ea, fa, eb, fb)
    assert streamed == batch


# ---------------------------------------------------------------------------
# composite pipelines: multiple stateful stages chained


@pytest.mark.parametrize("seed", range(4))
def test_three_stage_pipeline_consistency(seed):
    """filter -> groupby -> join -> groupby: a retraction entering stage
    one must cascade correctly through three stateful stages."""
    rng = random.Random(700 + seed)
    epochs, final = _random_history(rng, n_keys=10, n_epochs=12)

    def build(t):
        flt = t.filter(t.v % 3 != 0)
        per_g = flt.groupby(flt.g).reduce(
            flt.g, n=pw.reducers.count(), s=pw.reducers.sum(flt.v)
        )
        j = flt.join(per_g, flt.g == per_g.g)
        enriched = j.select(flt.k, flt.g, share=flt.v * 100 // pw.right.s)
        return enriched.groupby(enriched.g).reduce(
            enriched.g, total_share=pw.reducers.sum(enriched.share)
        )

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(4))
def test_flatten_then_aggregate_consistency(seed):
    rng = random.Random(800 + seed)
    epochs, final = _random_history(rng, n_keys=8, n_epochs=10)

    def build(t):
        tup = t.select(t.g, parts=pw.apply(lambda v: tuple(range(v % 4)), t.v))
        flat = tup.flatten(tup.parts)
        return flat.groupby(flat.g).reduce(
            flat.g, n=pw.reducers.count(), s=pw.reducers.sum(flat.parts)
        )

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(4))
def test_double_groupby_rollup_consistency(seed):
    """Two-level rollup (g,v)->(g)->global with avg in the middle."""
    rng = random.Random(900 + seed)
    epochs, final = _random_history(rng, n_keys=10, n_epochs=10)

    def build(t):
        lvl1 = t.groupby(t.g, t.v).reduce(t.g, t.v, n=pw.reducers.count())
        lvl2 = lvl1.groupby(lvl1.g).reduce(
            lvl1.g,
            distinct=pw.reducers.count(),
            biggest=pw.reducers.max(lvl1.v),
        )
        total = lvl2.groupby().reduce(
            groups=pw.reducers.count(),
            overall_max=pw.reducers.max(lvl2.biggest),
        )
        return total

    streamed, batch = _run_both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(3))
def test_deduplicate_downstream_of_aggregates(seed):
    """Append-only deduplicate fed by a changing aggregate: accepted
    values form a monotone sequence regardless of churn order."""
    rng = random.Random(1000 + seed)
    epochs, _final = _random_history(rng, n_keys=6, n_epochs=10)

    pw.G.clear()
    t = _stream_table(epochs)
    agg = t.groupby().reduce(total=pw.reducers.sum(pw.this.v))
    best = agg.deduplicate(
        value=pw.this.total,
        acceptor=lambda new, old: old is None or new > old,
    )
    history: list = []
    pw.io.subscribe(
        best, on_change=lambda k, row, tm, add: history.append((add, row["total"]))
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    accepted = [v for add, v in history if add]
    assert accepted == sorted(set(accepted))  # strictly increasing record
