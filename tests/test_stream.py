"""Streaming semantics: update streams, retractions, epoch consistency."""

import pathway_tpu as pw
from tests.utils import T, assert_table_equality_wo_index, stream_rows


def test_stream_markdown_final_state():
    t = T(
        """
        id | v | __time__ | __diff__
        1  | 1 | 2        | 1
        2  | 5 | 2        | 1
        1  | 1 | 4        | -1
        1  | 7 | 4        | 1
        """
    )
    expected = T(
        """
        id | v
        1  | 7
        2  | 5
        """
    )
    from tests.utils import assert_table_equality

    assert_table_equality(t, expected)


def test_stream_groupby_updates():
    t = T(
        """
        id | g | v | __time__ | __diff__
        1  | a | 1 | 2        | 1
        2  | a | 2 | 4        | 1
        3  | b | 9 | 4        | 1
        """
    )
    res = t.groupby(t.g).reduce(t.g, s=pw.reducers.sum(t.v))
    stream = stream_rows(res)
    # epoch 1: (a,1)+1 ; epoch 2: (a,1)-1, (a,3)+1, (b,9)+1
    diffs = [(vals, diff) for _, vals, _, diff in stream]
    assert (("a", 1), 1) in diffs
    assert (("a", 1), -1) in diffs
    assert (("a", 3), 1) in diffs
    assert (("b", 9), 1) in diffs
    assert_table_equality_wo_index(
        res,
        T(
            """
            g | s
            a | 3
            b | 9
            """
        ),
    )


def test_stream_join_incremental():
    left = T(
        """
        id | k | a | __time__ | __diff__
        1  | x | 1 | 2        | 1
        2  | y | 2 | 4        | 1
        """
    )
    right = T(
        """
        id | k | b  | __time__ | __diff__
        7  | x | 10 | 2        | 1
        8  | y | 20 | 6        | 1
        """
    )
    res = left.join(right, left.k == right.k).select(pw.left.a, pw.right.b)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | b
            1 | 10
            2 | 20
            """
        ),
    )


def test_stream_retraction_in_filter():
    t = T(
        """
        id | v | __time__ | __diff__
        1  | 10 | 2       | 1
        1  | 10 | 4       | -1
        """
    )
    res = t.filter(t.v > 5)
    stream = stream_rows(res)
    assert len(stream) == 2
    assert stream[0][3] == 1 and stream[1][3] == -1
    from tests.utils import _rows_of

    assert _rows_of(res) == {}


def test_deduplicate_streaming():
    t = T(
        """
        id | v | __time__ | __diff__
        1  | 3 | 2        | 1
        2  | 1 | 4        | 1
        3  | 5 | 6        | 1
        """
    )
    res = t.deduplicate(value=pw.this.v, acceptor=lambda new, old: old is None or new > old)
    stream = stream_rows(res)
    vals = [(v[0], d) for _, v, _, d in stream]
    assert vals == [(3, 1), (3, -1), (5, 1)]
