"""Unit tests for the span recorder, flight recorder, and critical-path
attribution (pathway_tpu/internals/tracing.py + analysis/tracecrit.py)."""

import json
import os
import threading
import time

import pytest

from pathway_tpu.analysis import tracecrit
from pathway_tpu.internals import tracing


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.configure(
        PATHWAY_TRACE="1",
        PATHWAY_TRACE_SAMPLE="1.0",
        PATHWAY_TRACE_TAIL_MS=None,
        PATHWAY_TRACE_RING=None,
        PATHWAY_TRACE_DIR=None,
    )
    tracing.reset()
    yield
    tracing.configure(
        PATHWAY_TRACE=None,
        PATHWAY_TRACE_SAMPLE=None,
        PATHWAY_TRACE_TAIL_MS=None,
        PATHWAY_TRACE_RING=None,
        PATHWAY_TRACE_DIR=None,
    )
    tracing.reset()


def _events(**kw):
    kw.setdefault("all_spans", True)
    return tracing.chrome_events(**kw)


# ------------------------------------------------------------- record path


def test_record_span_lands_in_ring_with_context_identity():
    ctx = tracing.new_trace()
    t0 = tracing.now_ns()
    sid = tracing.record_span("work", t0, t0 + 1000, ctx=ctx, args={"k": 3})
    assert sid != 0
    (ev,) = [e for e in _events() if e["name"] == "work"]
    assert ev["ph"] == "X"
    assert ev["args"]["trace_id"] == ctx.trace_id
    assert ev["args"]["parent"] == ctx.span_id
    assert ev["args"]["span_id"] == sid
    assert ev["args"]["k"] == 3
    assert ev["dur"] == pytest.approx(1.0)  # µs


def test_record_span_disabled_returns_zero_and_records_nothing():
    tracing.configure(PATHWAY_TRACE="0")
    ctx = tracing.TraceContext(1, 1)
    assert tracing.record_span("off", 0, 1, ctx=ctx) == 0
    assert _events() == []


def test_record_spans_batch_shares_parent_and_orders_ids():
    ctx = tracing.new_trace()
    t = tracing.now_ns()
    tracing.record_spans(
        ctx,
        [("a", t, t + 10, None), ("b", t + 10, t + 20, None),
         ("c", t + 20, t + 30, {"n": 1})],
    )
    evs = {e["name"]: e for e in _events() if e["name"] in "abc"}
    assert set(evs) == {"a", "b", "c"}
    for ev in evs.values():
        assert ev["args"]["trace_id"] == ctx.trace_id
        assert ev["args"]["parent"] == ctx.span_id
    ids = [evs[n]["args"]["span_id"] for n in "abc"]
    assert ids == sorted(ids) and len(set(ids)) == 3
    assert evs["c"]["args"]["n"] == 1


def test_span_cm_nests_and_parents_onto_enclosing_span():
    ctx = tracing.new_trace()
    with tracing.use(ctx):
        with tracing.span("outer") as outer:
            with tracing.span("inner"):
                pass
    by_name = {e["name"]: e for e in _events()}
    assert by_name["outer"]["args"]["parent"] == ctx.span_id
    assert by_name["inner"]["args"]["parent"] == outer.span_id
    assert by_name["inner"]["args"]["trace_id"] == ctx.trace_id


def test_span_cm_contextless_records_unsampled_zero_trace():
    with tracing.span("orphan"):
        pass
    (ev,) = [e for e in _events() if e["name"] == "orphan"]
    assert ev["args"]["trace_id"] == 0
    # context-free spans are flight-recorder noise floor: exported even
    # without all_spans
    assert [e["name"] for e in tracing.chrome_events()] == ["orphan"]


def test_span_cm_toggle_on_mid_block_records_nothing():
    tracing.configure(PATHWAY_TRACE="0")
    cm = tracing.span("flip", ctx=tracing.TraceContext(9, 9))
    cm.__enter__()
    tracing.configure(PATHWAY_TRACE="1")
    cm.__exit__(None, None, None)
    assert _events() == []


def test_set_ambient_swaps_and_restores():
    ctx = tracing.new_trace()
    assert tracing.current() is None
    prev = tracing.set_ambient(ctx)
    assert prev is None and tracing.current() is ctx
    assert tracing.set_ambient(prev) is ctx
    assert tracing.current() is None


def test_ring_wraps_keeping_most_recent_spans():
    tracing.configure(PATHWAY_TRACE_RING="64")
    tracing.reset()
    ctx = tracing.new_trace()
    for i in range(200):
        tracing.record_span(f"s{i}", i, i + 1, ctx=ctx)
    names = [e["name"] for e in _events()]
    assert len(names) == 64
    assert names[-1] == "s199" and "s0" not in names


def test_span_ids_unique_across_threads():
    ctx = tracing.new_trace()
    done = []

    def work(tag):
        for i in range(50):
            tracing.record_span(f"{tag}", i, i + 1, ctx=ctx)
        done.append(tag)

    ts = [threading.Thread(target=work, args=(f"t{j}",)) for j in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(done) == 4
    ids = [e["args"]["span_id"] for e in _events() if e["name"].startswith("t")]
    assert len(ids) == 200 and len(set(ids)) == 200


# -------------------------------------------------- sampling + tail keep


def test_head_sampling_governs_export_not_recording():
    tracing.configure(PATHWAY_TRACE_SAMPLE="0.0")
    ctx = tracing.new_trace()
    assert ctx.sampled is False
    tracing.record_span("hidden", 0, 1000, ctx=ctx)
    # not exported by default...
    assert [e for e in tracing.chrome_events() if e["name"] == "hidden"] == []
    # ...but the flight recorder still holds it
    assert [e for e in _events() if e["name"] == "hidden"]


def test_tail_keep_resurrects_slow_unsampled_request():
    tracing.configure(PATHWAY_TRACE_SAMPLE="0.0", PATHWAY_TRACE_TAIL_MS="1")
    ctx = tracing.new_trace()
    tracing.record_span("slow_req", ctx.t0_ns, ctx.t0_ns + 5_000_000, ctx=ctx)
    tracing.finish_request(ctx, ctx.t0_ns + 5_000_000)  # 5ms > 1ms threshold
    assert [e for e in tracing.chrome_events() if e["name"] == "slow_req"]


def test_fast_unsampled_request_stays_hidden():
    tracing.configure(PATHWAY_TRACE_SAMPLE="0.0", PATHWAY_TRACE_TAIL_MS="1")
    ctx = tracing.new_trace()
    tracing.record_span("fast_req", ctx.t0_ns, ctx.t0_ns + 10_000, ctx=ctx)
    tracing.finish_request(ctx, ctx.t0_ns + 10_000)  # 10µs < 1ms threshold
    assert [e for e in tracing.chrome_events() if e["name"] == "fast_req"] == []


# ------------------------------------------------------- context on wire


def test_trace_context_wire_roundtrip():
    ctx = tracing.TraceContext(123, 456, sampled=False)
    back = tracing.TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.sampled) == (123, 456, False)
    assert tracing.TraceContext.from_wire("garbage") is None
    assert tracing.TraceContext.from_wire(None) is None


# ----------------------------------------------------- dump + merge paths


def test_dump_and_merge_trace_dir_stitch_ranks(tmp_path):
    spool = str(tmp_path)
    tracing.configure(PATHWAY_TRACE_DIR=spool)
    ctx = tracing.new_trace()
    tracing.set_rank(0)
    tracing.record_span("r0_work", 0, 1000, ctx=ctx)
    assert tracing.flush("test")
    # same machine-wide ids, different "process": re-stamp the rank the
    # way a supervised worker would and flush again
    tracing.reset()
    tracing.configure(PATHWAY_TRACE_DIR=spool)
    tracing.set_rank(1)
    tracing.record_span("r1_work", 2000, 3000, ctx=ctx)
    assert tracing.flush("test")
    merged = tracing.merge_trace_dir(spool)
    assert merged and os.path.exists(merged)
    with open(merged) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1}
    assert {e["name"] for e in evs} == {"r0_work", "r1_work"}
    tracing.set_rank(0)


def test_merge_trace_dir_empty_and_missing(tmp_path):
    assert tracing.merge_trace_dir(str(tmp_path)) is None
    assert tracing.merge_trace_dir(str(tmp_path / "nope")) is None


def test_flush_without_spool_is_noop():
    assert tracing.flush("test") is None


def test_dump_stacks_names_this_thread():
    text = tracing.dump_stacks()
    assert "--- Thread" in text
    assert "test_dump_stacks_names_this_thread" in text


# -------------------------------------------------------------- tracecrit


def _synthetic_trace(trace_id=7, base=1000.0):
    """root(10ms) -> [queue(4ms), work(5ms) -> inner_search(3ms)]"""

    def ev(name, sid, parent, ts, dur):
        return {
            "ph": "X", "name": name, "pid": 0, "tid": "t",
            "ts": ts, "dur": dur,
            "args": {"trace_id": trace_id, "span_id": sid, "parent": parent},
        }

    return [
        ev("serve_e2e", 70, trace_id, base, 10_000.0),
        ev("serve_sched_wait", 71, 70, base, 4_000.0),
        ev("generate", 72, 70, base + 4_000.0, 5_000.0),
        ev("search", 73, 72, base + 4_500.0, 3_000.0),
    ]


def test_attribute_exclusive_times_partition_the_root():
    info = tracecrit.attribute(_synthetic_trace())
    by = info["by_stage_ms"]
    assert by["serve_sched_wait"] == pytest.approx(4.0)
    assert by["generate"] == pytest.approx(2.0)  # 5ms minus 3ms child
    assert by["search"] == pytest.approx(3.0)
    assert by["serve_e2e"] == pytest.approx(1.0)  # 10 - (4 + 5) covered
    assert sum(by.values()) == pytest.approx(info["wall_ms"])
    cats = info["by_category_ms"]
    assert cats["queue_wait"] == pytest.approx(4.0)
    assert cats["device"] == pytest.approx(5.0)


def test_critical_path_descends_into_biggest_child():
    path = tracecrit.critical_path(_synthetic_trace())
    assert [p["stage"] for p in path] == ["serve_e2e", "generate", "search"]
    assert path[0]["ms"] == pytest.approx(10.0)


def test_connected_traces_flags_orphaned_parent():
    good = _synthetic_trace(trace_id=7)
    bad = _synthetic_trace(trace_id=8)
    bad[3]["args"]["parent"] = 99999  # points at a span nobody recorded
    conn = tracecrit.connected_traces(good + bad)
    assert conn[7] is True and conn[8] is False


def test_report_rolls_up_quantiles_and_critical_path():
    events = []
    for i in range(10):
        events += _synthetic_trace(trace_id=100 + i, base=i * 100_000.0)
    rep = tracecrit.report(events)
    assert rep["requests"] == 10
    assert rep["p50"]["wall_ms"] == pytest.approx(10.0)
    assert rep["p99"]["wall_ms"] == pytest.approx(10.0)
    assert rep["mean_by_category_ms"]["device"] == pytest.approx(5.0)
    assert [s["stage"] for s in rep["slowest"]["critical_path"]][0] == "serve_e2e"
    assert tracecrit.report([]) == {"requests": 0}


def test_report_over_real_recorded_spans():
    """End-to-end: record via the real API, export, attribute."""
    ctx = tracing.new_trace()
    with tracing.use(ctx):
        with tracing.span("serve_e2e"):
            with tracing.span("serve_sched_wait"):
                time.sleep(0.002)
            with tracing.span("generate"):
                time.sleep(0.003)
    tracing.finish_request(ctx)
    rep = tracecrit.report(_events())
    assert rep["requests"] == 1
    p50 = rep["p50"]["by_category_ms"]
    assert p50["queue_wait"] >= 1.0
    assert p50["device"] >= 2.0
    conn = tracecrit.connected_traces(_events())
    assert conn[ctx.trace_id] is True
