"""Temporal stdlib: windows, behaviors, interval/asof/asof_now joins."""

import pathway_tpu as pw
from pathway_tpu.stdlib import temporal
from tests.utils import T, assert_table_equality_wo_index, run_to_rows


def test_tumbling_window_reduce():
    t = T(
        """
    t  | v
    1  | 10
    2  | 20
    11 | 1
    12 | 2
    25 | 5
    """
    )
    res = t.windowby(pw.this.t, window=temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
    )
    rows = sorted((r[1], r[2], r[3]) for r in run_to_rows(res))
    assert rows == [(0, 30, 2), (10, 3, 2), (20, 5, 1)]


def test_sliding_window_assigns_multiple():
    t = T(
        """
    t | v
    5 | 1
    """
    )
    res = t.windowby(
        pw.this.t, window=temporal.sliding(hop=2, duration=6)
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    starts = sorted(r[1] for r in run_to_rows(res))
    # windows [0,6) [2,8) [4,10) contain t=5
    assert starts == [0, 2, 4]


def test_session_window():
    t = T(
        """
    t  | v
    1  | 1
    2  | 1
    3  | 1
    20 | 1
    21 | 1
    """
    )
    res = t.windowby(
        pw.this.t, window=temporal.session(max_gap=5)
    ).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        n=pw.reducers.count(),
    )
    rows = sorted((r[1], r[2], r[3]) for r in run_to_rows(res))
    assert rows == [(1, 3, 3), (20, 21, 2)]


def test_window_behavior_forget():
    """keep_results=False drops windows once the watermark passes
    window_end + cutoff (reference forget semantics)."""
    t = T(
        """
    t   | v   | __time__
    1   | 1   | 2
    2   | 1   | 2
    30  | 1   | 4
    """
    )
    res = t.windowby(
        pw.this.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.common_behavior(cutoff=5, keep_results=False),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    rows = run_to_rows(res)
    # window [0,10) expired when t=30 arrived (30 >= 10+5); only [30,40) left
    assert [(r[1], r[2]) for r in rows] == [(30, 1)]


def test_exactly_once_behavior_buffers():
    t = T(
        """
    t   | v   | __time__
    1   | 1   | 2
    2   | 1   | 2
    11  | 1   | 4
    30  | 1   | 6
    """
    )
    res = t.windowby(
        pw.this.t,
        window=temporal.tumbling(duration=10),
        behavior=temporal.exactly_once_behavior(),
    ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())
    from tests.utils import stream_rows

    stream = stream_rows(res)
    # window [0,10) must be emitted exactly once (no incremental revision)
    w0 = [s for s in stream if s[1][1] == 0]
    assert len(w0) == 1 and w0[0][3] == 1 and w0[0][1][2] == 2


def test_interval_join_inner():
    a = T(
        """
    t | k | va
    1 | x | a1
    5 | x | a5
    """
    )
    b = T(
        """
    t | k | vb
    2 | x | b2
    9 | x | b9
    """
    )
    res = temporal.interval_join(
        a, b, a.t, b.t, temporal.interval(-1, 2), pw.left.k == pw.right.k
    ).select(va=pw.left.va, vb=pw.right.vb)
    rows = sorted(run_to_rows(res))
    # pairs with b.t - a.t in [-1, 2]: (a1,b2); (a5, b..): 9-5=4 no; 2-5=-3 no
    assert rows == [("a1", "b2")]


def test_interval_join_outer_unmatched():
    a = T(
        """
    t | va
    1 | a1
    9 | a9
    """
    )
    b = T(
        """
    t | vb
    2 | b2
    """
    )
    res = temporal.interval_join_outer(
        a, b, a.t, b.t, temporal.interval(-1, 1)
    ).select(va=pw.left.va, vb=pw.right.vb)
    rows = sorted(run_to_rows(res), key=str)
    assert (("a1", "b2")) in rows
    assert ("a9", None) in rows


def test_asof_join_backward():
    trades = T(
        """
    t  | k | price
    3  | x | 100
    7  | x | 101
    """
    )
    quotes = T(
        """
    t | k | quote
    1 | x | 99
    5 | x | 100
    9 | x | 102
    """
    )
    res = temporal.asof_join(
        trades, quotes, trades.t, quotes.t, pw.left.k == pw.right.k
    ).select(price=pw.left.price, quote=pw.right.quote)
    rows = sorted(run_to_rows(res))
    # t=3 -> quote at 1; t=7 -> quote at 5
    assert rows == [(100, 99), (101, 100)]


def test_asof_now_join_no_revision():
    """asof_now answers once; later right-side rows don't revise."""
    import threading
    import time as _time

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    class RightSubject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="x", r="r1")
            self.commit()
            _time.sleep(0.5)
            self.next(k="x", r="r2")
            self.commit()

    class LeftSubject(pw.io.python.ConnectorSubject):
        def run(self):
            _time.sleep(0.25)  # after r1, before r2
            self.next(k="x", l="l1")
            self.commit()

    class RightSchema(pw.Schema):
        k: str
        r: str

    class LeftSchema(pw.Schema):
        k: str
        l: str

    left = pw.io.python.read(LeftSubject(), schema=LeftSchema)
    right = pw.io.python.read(RightSubject(), schema=RightSchema)
    res = temporal.asof_now_join(left, right, pw.left.k == pw.right.k).select(
        l=pw.left.l, r=pw.right.r
    )
    updates = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: updates.append(
            (is_addition, row["r"])
        ),
    )
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    th = threading.Thread(target=sched.run)
    th.start()
    th.join(timeout=10)
    assert not th.is_alive()
    adds = [u for u in updates if u[0]]
    # the left row is answered against the right state at its arrival
    # (r1 only) and never revised when r2 arrives later
    assert adds == [(True, "r1")]
    assert not [u for u in updates if not u[0]]  # no retractions


def test_window_join():
    a = T(
        """
    t | va
    1 | a1
    11| a11
    """
    )
    b = T(
        """
    t | vb
    2 | b2
    12| b12
    """
    )
    res = temporal.window_join(
        a, b, a.t, b.t, temporal.tumbling(duration=10)
    ).select(va=pw.left.va, vb=pw.right.vb)
    rows = sorted(run_to_rows(res))
    assert rows == [("a1", "b2"), ("a11", "b12")]


def test_intervals_over_window():
    """intervals_over: one output row per `at` probe, aggregating source
    rows within [at+lower, at+upper] (reference _window.py:595+)."""
    data = T(
        """
    t  | v
    1  | 10
    3  | 30
    5  | 50
    9  | 90
    """
    )
    probes = T(
        """
    at
    2
    6
    """
    )
    res = data.windowby(
        data.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-2, upper_bound=1
        ),
    ).reduce(
        total=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
    )
    res = res.select(at=pw.this._pw_window_start, total=pw.this.total, n=pw.this.n)
    rows = sorted(run_to_rows(res))
    # at=2: t in [0,3] -> 10+30; at=6: t in [4,7] -> 50
    assert rows == [(2, 40, 2), (6, 50, 1)]


def test_sliding_window_ratio():
    t = T(
        """
    t | v
    0 | 1
    2 | 1
    4 | 1
    """
    )
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=2, ratio=2)
    ).reduce(
        n=pw.reducers.count(),
    )
    res = res.select(s=pw.this._pw_window_start, n=pw.this.n)
    rows = sorted(run_to_rows(res))
    # duration = hop * ratio = 4; windows [-2,2),[0,4),[2,6),[4,8)
    assert rows == [(-2, 1), (0, 2), (2, 2), (4, 1)]


def test_session_window_predicate():
    t = T(
        """
    t  | v
    1  | 1
    2  | 1
    10 | 1
    """
    )
    res = t.windowby(
        t.t,
        window=pw.temporal.session(predicate=lambda a, b: abs(a - b) <= 3),
    ).reduce(n=pw.reducers.count())
    res = res.select(n=pw.this.n)
    assert sorted(run_to_rows(res)) == [(1,), (2,)]


def test_interval_join_left_and_right():
    left = T(
        """
    t | a
    1 | l1
    5 | l2
    """
    )
    right = T(
        """
    t | b
    2 | r1
    9 | r2
    """
    )
    lres = left.interval_join_left(
        right, left.t, right.t, pw.temporal.interval(-1, 1)
    ).select(a=left.a, b=right.b)
    assert sorted(run_to_rows(lres), key=repr) == [("l1", "r1"), ("l2", None)]
    rres = left.interval_join_right(
        right, left.t, right.t, pw.temporal.interval(-1, 1)
    ).select(a=left.a, b=right.b)
    assert sorted(run_to_rows(rres), key=repr) == [("l1", "r1"), (None, "r2")]


def test_asof_join_directions():
    left = T(
        """
    t | a
    3 | x
    7 | y
    """
    )
    right = T(
        """
    t | p
    2 | 20
    5 | 50
    8 | 80
    """
    )
    fwd = left.asof_join(
        right, left.t, right.t, how=pw.JoinMode.LEFT, direction="forward"
    ).select(a=left.a, p=right.p)
    assert sorted(run_to_rows(fwd)) == [("x", 50), ("y", 80)]
    nearest = left.asof_join(
        right, left.t, right.t, how=pw.JoinMode.LEFT, direction="nearest"
    ).select(a=left.a, p=right.p)
    assert sorted(run_to_rows(nearest)) == [("x", 20), ("y", 80)]
