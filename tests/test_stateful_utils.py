"""Stateful deduplicate acceptor semantics, per-instance isolation under
streaming, and the stdlib col utilities (unpack_col, apply_all_rows) —
reference ``stdlib/stateful/deduplicate.py`` + ``stdlib/utils/col.py``.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from tests.utils import T, run_to_rows


def test_deduplicate_acceptor_keeps_increasing_values():
    """Classic monotone acceptor: only strictly greater values replace
    the held row; everything else is suppressed."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    v  | __time__ | __diff__
    3  | 2        | 1
    1  | 4        | 1
    7  | 6        | 1
    5  | 8        | 1
    """
    )
    d = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: old is None or new > old
    )
    history: list = []
    pw.io.subscribe(
        d, on_change=lambda k, row, tm, add: history.append((add, row["v"]))
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # accepted sequence: 3 then 7 (1 and 5 rejected); retractions pair up
    accepted = [v for add, v in history if add]
    assert accepted == [3, 7]
    final = [v for add, v in history if add][-1]
    assert final == 7


def test_deduplicate_per_instance_streams_independently():
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    g | v | __time__ | __diff__
    a | 1 | 2        | 1
    b | 9 | 2        | 1
    a | 5 | 4        | 1
    b | 2 | 4        | 1
    """
    )
    d = t.deduplicate(
        value=pw.this.v,
        instance=pw.this.g,
        acceptor=lambda new, old: old is None or new > old,
    )
    rows = sorted(run_to_rows(d.select(pw.this.g, pw.this.v)))
    # instance a accepted 1 then 5; instance b accepted 9, rejected 2
    assert rows == [("a", 5), ("b", 9)]


def test_deduplicate_acceptor_exception_contained():
    pw.G.clear()
    t = T(
        """
    v
    1
    2
    """
    )

    def explosive(new, old):
        if new == 2:
            raise RuntimeError("acceptor exploded")
        return old is None

    d = t.deduplicate(value=pw.this.v, acceptor=explosive)
    err = pw.global_error_log()
    cap_d = d._capture_node()
    cap_e = err._capture_node()
    ctx = pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # the run survives; the error is logged; the held row remains
    assert any("acceptor" in v[0] for v in ctx.state(cap_e)["rows"].values())
    held = [v[0] for v in ctx.state(cap_d)["rows"].values()]
    assert held == [1]


def test_unpack_col_expands_tuples():
    from pathway_tpu.stdlib.utils.col import unpack_col

    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(packed=tuple),
        [((1, "x"),), ((2, "y"),)],
    )
    out = unpack_col(t.packed, "num", "label")
    assert out.column_names() == ["num", "label"]
    assert sorted(run_to_rows(out)) == [(1, "x"), (2, "y")]


def test_apply_all_rows_sees_whole_column():
    from pathway_tpu.stdlib.utils.col import apply_all_rows

    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(v=int), [(1,), (2,), (3,)]
    )

    def normalize(vs):
        total = sum(vs)
        return [v / total for v in vs]

    out = apply_all_rows(t.v, fun=normalize, result_col_name="share")
    rows = sorted(r[-1] for r in run_to_rows(out))
    assert rows == pytest.approx([1 / 6, 2 / 6, 3 / 6])


def test_deduplicate_is_append_only():
    """Deduplicate consumes ADDITIONS only (append-only source contract,
    like the reference's persisted deduplicate): retracting the held row
    upstream does not reopen the slot."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    v | __time__ | __diff__
    5 | 2        | 1
    5 | 4        | -1
    1 | 6        | 1
    """
    )
    d = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: old is None or new > old
    )
    rows = [v[0] for v in run_to_rows(d.select(pw.this.v))]
    assert rows == [5]  # the retraction is ignored; 1 < 5 rejected
