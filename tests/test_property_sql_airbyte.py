"""Property/differential tests for SQL set operations and the airbyte
state machinery (round-4 verdict item 3: these areas rested on a
handful of example-based tests each).

- SQL: randomized table pairs; INTERSECT / EXCEPT / UNION [ALL] /
  ``[NOT] IN (SELECT ...)`` are checked against independently computed
  Python set/bag semantics, including NULL probes and duplicates
  (reference semantics: SQL set ops deduplicate, set membership with
  NULL is three-valued).
- Airbyte: the StateTracker's fold is checked for the protocol
  invariants (last-writer-wins per stream, LEGACY superseded by
  stream/global states, envelope round-trip idempotence) over random
  message sequences — mirroring the reference's state folding
  (airbyte-serverless logic.py:68-131 role).
"""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.io.airbyte import AirbyteStateTracker as StateTracker
from tests.utils import run_to_rows


def _rand_rows(rng: random.Random, n: int, vals: int) -> list[tuple]:
    return [
        (rng.randrange(vals), rng.choice(["p", "q", "r"]))
        for _ in range(n)
    ]


def _table(rows: list[tuple]) -> pw.Table:
    return pw.debug.table_from_rows(
        pw.schema_from_types(x=int, y=str), rows
    )


@pytest.mark.parametrize("seed", range(8))
def test_sql_set_ops_match_set_semantics(seed):
    rng = random.Random(seed)
    rows_a = _rand_rows(rng, rng.randrange(0, 14), 5)
    rows_b = _rand_rows(rng, rng.randrange(0, 14), 5)
    pw.G.clear()
    a, b = _table(rows_a), _table(rows_b)
    sa, sb = set(rows_a), set(rows_b)

    inter = pw.sql("SELECT x, y FROM a INTERSECT SELECT x, y FROM b", a=a, b=b)
    assert sorted(run_to_rows(inter)) == sorted(sa & sb), (rows_a, rows_b)

    exc = pw.sql("SELECT x, y FROM a EXCEPT SELECT x, y FROM b", a=a, b=b)
    assert sorted(run_to_rows(exc)) == sorted(sa - sb), (rows_a, rows_b)

    uni = pw.sql("SELECT x, y FROM a UNION SELECT x, y FROM b", a=a, b=b)
    assert sorted(run_to_rows(uni)) == sorted(sa | sb), (rows_a, rows_b)

    # UNION ALL keeps duplicates (bag semantics)
    uall = pw.sql(
        "SELECT x, y FROM a UNION ALL SELECT x, y FROM b", a=a, b=b
    )
    assert sorted(run_to_rows(uall)) == sorted(rows_a + rows_b)


@pytest.mark.parametrize("seed", range(8))
def test_sql_in_subquery_matches_membership(seed):
    rng = random.Random(100 + seed)
    rows_a = _rand_rows(rng, rng.randrange(1, 14), 6)
    rows_b = _rand_rows(rng, rng.randrange(0, 10), 6)
    pw.G.clear()
    a, b = _table(rows_a), _table(rows_b)
    members = {x for x, _y in rows_b}

    got = pw.sql(
        "SELECT x, y FROM a WHERE x IN (SELECT x FROM b)", a=a, b=b
    )
    # semi-join: each qualifying A row appears exactly once per occurrence
    assert sorted(run_to_rows(got)) == sorted(
        r for r in rows_a if r[0] in members
    ), (rows_a, rows_b)

    got = pw.sql(
        "SELECT x, y FROM a WHERE x NOT IN (SELECT x FROM b)", a=a, b=b
    )
    assert sorted(run_to_rows(got)) == sorted(
        r for r in rows_a if r[0] not in members
    )


def test_sql_in_subquery_null_handling_matches_documented_contract():
    """NULL handling follows the engine's documented contract
    (internals/sql.py _apply_in_subquery): a NULL PROBE never matches —
    IN and NOT IN both drop it (three-valued logic) — while a NULL
    *inside* the subquery is a non-matching value (a deliberate,
    documented deviation from the standard's everything-is-UNKNOWN
    behavior, which is almost never what a query means)."""
    pw.G.clear()
    a = pw.debug.table_from_rows(
        pw.schema_from_types(x=int, y=str),
        [(1, "p"), (2, "q"), (None, "n")],
    )
    b = pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(1,), (None,)]
    )
    # NULL probe (None, 'n') drops from BOTH results
    got = pw.sql("SELECT x, y FROM a WHERE x IN (SELECT x FROM b)", a=a, b=b)
    assert sorted(run_to_rows(got)) == [(1, "p")]
    got = pw.sql("SELECT x, y FROM a WHERE x NOT IN (SELECT x FROM b)", a=a, b=b)
    assert sorted(run_to_rows(got)) == [(2, "q")]


def test_sql_set_ops_precedence_and_chaining():
    """A UNION B EXCEPT C parses left-to-right (standard precedence:
    INTERSECT binds tighter than UNION/EXCEPT)."""
    pw.G.clear()
    a = _table([(1, "p"), (2, "p")])
    b = _table([(2, "p"), (3, "p")])
    c = _table([(3, "p")])
    got = pw.sql(
        "SELECT x, y FROM a UNION SELECT x, y FROM b "
        "EXCEPT SELECT x, y FROM c",
        a=a, b=b, c=c,
    )
    assert sorted(run_to_rows(got)) == [(1, "p"), (2, "p")]
    # INTERSECT binds tighter: A UNION (B INTERSECT C)
    got = pw.sql(
        "SELECT x, y FROM a UNION SELECT x, y FROM b "
        "INTERSECT SELECT x, y FROM c",
        a=a, b=b, c=c,
    )
    assert sorted(run_to_rows(got)) == [(1, "p"), (2, "p"), (3, "p")]


# ---------------------------------------------------------------------------
# airbyte state folding


def _rand_state_msg(rng: random.Random) -> dict:
    kind = rng.choice(["LEGACY", "STREAM", "GLOBAL"])
    if kind == "LEGACY":
        return {"type": "LEGACY", "data": {"cursor": rng.randrange(100)}}
    if kind == "STREAM":
        return {
            "type": "STREAM",
            "stream": {
                "stream_descriptor": {"name": rng.choice("abc")},
                "stream_state": {"cursor": rng.randrange(100)},
            },
        }
    return {
        "type": "GLOBAL",
        "global": {
            "stream_states": [
                {
                    "stream_descriptor": {"name": rng.choice("abc")},
                    "stream_state": {"cursor": rng.randrange(100)},
                }
                for _ in range(rng.randrange(0, 3))
            ],
            "shared_state": (
                {"epoch": rng.randrange(10)} if rng.random() < 0.5 else None
            ),
        },
    }


def _model_fold(msgs: list[dict]) -> dict:
    """Independent model of the protocol: per-stream last-writer-wins,
    shared state from the last GLOBAL, legacy from the last LEGACY."""
    streams: dict = {}
    shared = None
    legacy = None
    for m in msgs:
        if m["type"] == "LEGACY":
            legacy = m["data"]
        elif m["type"] == "STREAM":
            s = m["stream"]
            streams[s["stream_descriptor"]["name"]] = s["stream_state"]
        else:
            for s in m["global"]["stream_states"]:
                streams[s["stream_descriptor"]["name"]] = s["stream_state"]
            shared = m["global"]["shared_state"]
    return {"streams": streams, "shared": shared, "legacy": legacy}


@pytest.mark.parametrize("seed", range(10))
def test_airbyte_state_folding_matches_model(seed):
    rng = random.Random(seed)
    msgs = [_rand_state_msg(rng) for _ in range(rng.randrange(1, 20))]
    tracker = StateTracker()
    for m in msgs:
        tracker.observe(m)
    model = _model_fold(msgs)
    env = tracker.envelope()
    if model["streams"] or model["shared"] is not None:
        assert env is not None and env["type"] == "GLOBAL"
        got_streams = {
            s["stream_descriptor"]["name"]: s["stream_state"]
            for s in env["global"]["stream_states"]
        }
        assert got_streams == model["streams"], msgs
        assert env["global"].get("shared_state") == (
            model["shared"] if model["shared"] is not None else None
        )
    elif model["legacy"] is not None:
        assert env == {"type": "LEGACY", "data": model["legacy"]}
    else:
        assert env is None


@pytest.mark.parametrize("seed", range(6))
def test_airbyte_envelope_round_trip_idempotent(seed):
    """load(envelope()) then envelope() again is a fixed point — the
    resume contract: feeding the rendered state back reproduces it."""
    rng = random.Random(50 + seed)
    tracker = StateTracker()
    for _ in range(rng.randrange(1, 15)):
        tracker.observe(_rand_state_msg(rng))
    env1 = tracker.envelope()
    fresh = StateTracker()
    fresh.load(env1)
    assert fresh.envelope() == env1


def test_airbyte_malformed_states_ignored():
    tracker = StateTracker()
    tracker.observe({"type": "LEGACY"})  # no data
    tracker.observe({"type": "STREAM"})  # no stream
    tracker.observe({"type": "STREAM", "stream": {"stream_state": {}}})  # no name
    tracker.observe({"type": "GLOBAL"})  # no global
    tracker.observe({"type": "WHATEVER"})
    assert tracker.envelope() is None
    # valid state still folds after garbage
    tracker.observe(
        {
            "type": "STREAM",
            "stream": {
                "stream_descriptor": {"name": "s"},
                "stream_state": {"cursor": 7},
            },
        }
    )
    env = tracker.envelope()
    assert env["global"]["stream_states"][0]["stream_state"] == {"cursor": 7}
