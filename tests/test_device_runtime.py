"""Runtime half of the device-safety story (ISSUE 20): the jit-compile
and transfer counters (``internals/device_counters.py``) cross-validated
against the static PW-J prediction.

The zero-recompile invariant: with no PW-J001 sites on the device
surface, a warmed serving loop must record exactly 0 new XLA compiles —
the counter sees ``jax.monitoring`` backend_compile events, which fire
once per real compile and never on an executable-cache hit.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pathway_tpu.internals import device_counters as devctr  # noqa: E402


@pytest.fixture(autouse=True)
def _installed():
    devctr.install()
    yield


def test_counter_sees_real_compiles_and_ignores_cache_hits():
    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    base = devctr.compile_count()
    f(jnp.ones((3,), jnp.float32)).block_until_ready()
    first = devctr.compile_count() - base
    assert first >= 1  # a fresh trace really compiled

    base = devctr.compile_count()
    for _ in range(5):
        f(jnp.ones((3,), jnp.float32)).block_until_ready()
    assert devctr.compile_count() - base == 0  # cache hits emit nothing


def test_shape_unstable_jit_records_a_compile_per_shape():
    """The storm PW-J001 predicts: every distinct length is a fresh
    trace+compile."""

    @jax.jit
    def f(x):
        return (x * x).sum()

    base = devctr.compile_count()
    for n in range(1, 5):
        f(jnp.ones((n,), jnp.float32)).block_until_ready()
    assert devctr.compile_count() - base >= 4


def test_warmed_ivf_serving_loop_records_zero_compiles():
    """Live cross-validation of the static sweep: the bucketed IVF
    search path, once warmed over a batch-size range, must hold the
    compile counter flat through arbitrary sizes in that range."""
    from pathway_tpu.parallel.ivf_knn import IvfKnnIndex

    dim = 16
    rng = np.random.default_rng(7)
    idx = IvfKnnIndex(dim, capacity=64, query_block=4)
    idx.add_batch(
        [f"d{i}" for i in range(96)],
        rng.standard_normal((96, dim)).astype(np.float32),
    )
    if not idx.trained:
        idx.train()

    sizes = list(range(1, 10))
    for nq in sizes:  # warmup: compiles land here, bounded by buckets
        idx.search(rng.standard_normal((nq, dim)).astype(np.float32), k=3)

    base = devctr.compile_count()
    for nq in sizes:
        rows = idx.search(
            rng.standard_normal((nq, dim)).astype(np.float32), k=3
        )
        assert len(rows) == nq
    assert devctr.compile_count() - base == 0


def test_transfer_counters_accumulate():
    snap0 = devctr.snapshot()
    devctr.record_h2d(4096)
    devctr.record_d2h(128)
    snap1 = devctr.snapshot()
    assert snap1["h2d_bytes"] - snap0["h2d_bytes"] == 4096
    assert snap1["h2d_transfers"] - snap0["h2d_transfers"] == 1
    assert snap1["d2h_bytes"] - snap0["d2h_bytes"] == 128
    assert snap1["d2h_transfers"] - snap0["d2h_transfers"] == 1


def test_ivf_search_accounts_its_transfers():
    from pathway_tpu.parallel.ivf_knn import IvfKnnIndex

    dim = 16
    rng = np.random.default_rng(11)
    idx = IvfKnnIndex(dim, capacity=64, query_block=4)
    idx.add_batch(
        [f"d{i}" for i in range(64)],
        rng.standard_normal((64, dim)).astype(np.float32),
    )
    if not idx.trained:
        idx.train()
    snap0 = devctr.snapshot()
    idx.search(rng.standard_normal((5, dim)).astype(np.float32), k=3)
    snap1 = devctr.snapshot()
    assert snap1["h2d_bytes"] > snap0["h2d_bytes"]
    assert snap1["d2h_bytes"] > snap0["d2h_bytes"]


def test_monitoring_joins_counters_with_static_prediction():
    """/status payload shape: live counters + the static sweep, so an
    operator can eyeball predicted-vs-observed in one place."""
    from pathway_tpu.internals import monitoring

    stats = monitoring.device_stats()
    assert "counters" in stats and "static" in stats
    assert "jit_compiles" in stats["counters"]
    assert stats["static"]["predicted_recompile_sites"] == 0


def test_metrics_expose_device_counters():
    import re

    import pathway_tpu as pw
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring_server import _metrics_text
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    t.select(b=pw.this.a)._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    devctr.record_h2d(64)  # ensure the counter block is non-empty
    body = _metrics_text(sched)
    m = re.search(r"pathway_tpu_jit_compiles_total (\d+)", body)
    assert m, body
    assert "pathway_tpu_h2d_bytes_total" in body
    assert "pathway_tpu_d2h_bytes_total" in body
    assert re.search(
        r"pathway_tpu_device_predicted_recompile_sites 0\b", body
    ), body
    pw.G.clear()


def test_snapshot_reports_listener_state():
    snap = devctr.snapshot()
    assert snap["listener_installed"] == 1  # numeric: metrics-friendly
