"""Interactive mode: cross-graph export/import and LiveTable
(reference ``internals/interactive.py:37-222``, engine export
``src/engine/dataflow/export.rs``)."""

import threading
import time

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from tests.utils import T


def test_export_snapshot_and_offsets():
    t = T(
        """
        a | b
        1 | x
        2 | y
        """
    )
    exp = pw.export_table(t.select(t.a, t.b))
    pw.run()
    assert exp.closed
    snap = sorted(exp.snapshot().values())
    assert snap == [(1, "x"), (2, "y")]
    batch, off, frontier, closed = exp.data_from_offset(0)
    assert len(batch) == 2 and closed and off == 2
    assert all(d == 1 for _t, _k, _v, d in batch)
    # incremental read from the end is empty
    batch2, off2, _, _ = exp.data_from_offset(off)
    assert batch2 == [] and off2 == off


def test_import_into_second_graph_preserves_keys_and_dtypes():
    t = T(
        """
        a | b
        1 | x
        2 | y
        3 | z
        """
    )
    exp = pw.export_table(t.select(t.a, t.b))
    pw.run()
    first_keys = set(exp.snapshot().keys())

    # a brand-new graph continues from the exported stream
    G.clear()
    imported = pw.import_table(exp)
    assert imported._dtypes["a"].name == "INT"
    filtered = imported.filter(imported.a >= 2).select(imported.a, imported.b)
    cap = filtered._capture_node()
    ctx = pw.run()
    rows = ctx.state(cap)["rows"]
    assert sorted(rows.values()) == [(2, "y"), (3, "z")]
    assert set(rows.keys()) <= first_keys  # row keys preserved across graphs


def test_live_table_streams_and_waits():
    pw.enable_interactive_mode()
    t = pw.debug.table_from_markdown(
        """
        w | v | __time__ | __diff__
        x | 1 | 2        | 1
        y | 2 | 4        | 1
        x | 1 | 6        | -1
        """
    )
    agg = t.select(t.w, t.v)
    lt = pw.LiveTable(agg)
    done = {}

    def runner():
        done["ctx"] = pw.run()

    th = threading.Thread(target=runner, daemon=True)
    th.start()
    th.join(30)
    assert not th.is_alive()
    assert lt.wait_closed(10)
    snap = lt.snapshot()
    assert sorted(snap.values()) == [("y", 2)]
    hist = lt.update_history()
    assert [(v, d) for _t, _k, v, d in hist] == [
        (("x", 1), 1),
        (("y", 2), 1),
        (("x", 1), -1),
    ]
    assert len(lt) == 1
    df = lt.to_pandas()
    assert list(df.columns) == ["w", "v"] and len(df) == 1
