"""Temporal operators under streaming churn: windows, interval joins
and asof joins must converge to the batch recomputation of the final
input (the same streaming/batch invariant as test_streaming_consistency,
applied to the temporal stdlib — reference temporal operators sit on
differential arrangements and inherit it for free; our buffer/retraction
implementations must earn it).
"""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal import session, sliding, tumbling
from tests.utils import run_to_rows


class _StreamSource(pw.io.python.ConnectorSubject):
    def __init__(self, epochs, schema):
        super().__init__()
        self._epochs = epochs
        self._sch = schema

    def run(self) -> None:
        from pathway_tpu.internals import keys as K
        from pathway_tpu.io._connector import coerce_row

        for epoch in self._epochs:
            for kind, key, row in epoch:
                k = K.ref_scalar("tmp", key)
                if kind == "add":
                    self._events.add(k, coerce_row(row, self._sch))
                else:
                    self._events.remove(k, coerce_row(row, self._sch))
            self.commit()


def _schema():
    return pw.schema_from_types(k=int, t=int, v=int)


def _history(rng: random.Random, n_keys=10, n_epochs=10, t_range=50):
    alive: dict[int, dict] = {}
    epochs = []
    for _ in range(n_epochs):
        epoch = []
        for _ in range(rng.randrange(1, 5)):
            key = rng.randrange(n_keys)
            if key in alive and rng.random() < 0.3:
                epoch.append(("remove", key, alive.pop(key)))
            elif key not in alive:
                row = {
                    "k": key,
                    "t": rng.randrange(t_range),
                    "v": rng.randrange(20),
                }
                epoch.append(("add", key, row))
                alive[key] = row
        if epoch:
            epochs.append(epoch)
    return epochs, list(alive.values())


def _stream(epochs):
    return pw.io.python.read(_StreamSource(epochs, _schema()), schema=_schema())


def _batch(rows):
    return pw.debug.table_from_rows(
        _schema(), [(r["k"], r["t"], r["v"]) for r in rows]
    )


def _both(build, epochs, final):
    pw.G.clear()
    streamed = sorted(run_to_rows(build(_stream(epochs))))
    pw.G.clear()
    batch = sorted(run_to_rows(build(_batch(final))))
    return streamed, batch


@pytest.mark.parametrize("seed", range(5))
def test_tumbling_window_consistency(seed):
    rng = random.Random(seed)
    epochs, final = _history(rng)

    def build(t):
        return t.windowby(t.t, window=tumbling(duration=10)).reduce(
            start=pw.this._pw_window_start,
            n=pw.reducers.count(),
            s=pw.reducers.sum(pw.this.v),
        )

    streamed, batch = _both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(5))
def test_sliding_window_consistency(seed):
    rng = random.Random(30 + seed)
    epochs, final = _history(rng)

    def build(t):
        return t.windowby(
            t.t, window=sliding(hop=5, duration=15)
        ).reduce(
            start=pw.this._pw_window_start,
            n=pw.reducers.count(),
            mx=pw.reducers.max(pw.this.v),
        )

    streamed, batch = _both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(5))
def test_session_window_consistency(seed):
    """Session windows are the hardest case: a deletion can split a
    session, an insertion can merge two."""
    rng = random.Random(60 + seed)
    epochs, final = _history(rng, t_range=40)

    def build(t):
        return t.windowby(t.t, window=session(max_gap=4)).reduce(
            n=pw.reducers.count(),
            lo=pw.reducers.min(pw.this.t),
            hi=pw.reducers.max(pw.this.t),
        )

    streamed, batch = _both(build, epochs, final)
    assert streamed == batch, (epochs, final)


@pytest.mark.parametrize("seed", range(4))
def test_interval_join_consistency(seed):
    rng = random.Random(90 + seed)
    ea, fa = _history(rng, n_keys=6, n_epochs=7, t_range=30)
    eb, fb = _history(rng, n_keys=6, n_epochs=7, t_range=30)

    def build_pair(a, b):
        j = a.interval_join(
            b, a.t, b.t, pw.temporal.interval(-3, 3)
        )
        return j.select(ta=a.t, tb=b.t, va=a.v, vb=b.v)

    pw.G.clear()
    streamed = sorted(run_to_rows(build_pair(_stream(ea), _stream(eb))))
    pw.G.clear()
    batch = sorted(run_to_rows(build_pair(_batch(fa), _batch(fb))))
    assert streamed == batch, (ea, eb)


@pytest.mark.parametrize("seed", range(4))
def test_asof_join_consistency(seed):
    """With equal right-side times the asof match is ambiguous and the
    engine's deterministic tie-break keys on internal row identity —
    which legitimately differs between the streamed and batch key
    spaces — so B timestamps are made unique up front.  Row dicts are
    shared between their add/remove events and the final state, so each
    dict is bumped at most once and every view stays aligned."""
    rng = random.Random(120 + seed)
    ea, fa = _history(rng, n_keys=6, n_epochs=7, t_range=30)
    eb, fb = _history(rng, n_keys=6, n_epochs=7, t_range=1000)
    used: set = set()
    bumped: set = set()
    for epoch in eb:
        for _kind, _key, row in epoch:
            if id(row) in bumped:
                continue
            bumped.add(id(row))
            while row["t"] in used:
                row["t"] += 1000
            used.add(row["t"])

    def build_pair(a, b):
        j = a.asof_join(b, a.t, b.t)
        return j.select(ta=a.t, tb=b.t, va=a.v, vb=b.v)

    pw.G.clear()
    streamed = sorted(run_to_rows(build_pair(_stream(ea), _stream(eb))))
    pw.G.clear()
    batch = sorted(run_to_rows(build_pair(_batch(fa), _batch(fb))))
    assert streamed == batch, (ea, eb)


@pytest.mark.parametrize("seed", range(3))
def test_windowed_groupby_instance_consistency(seed):
    """Windows keyed per instance column: per-key sessions evolve
    independently."""
    rng = random.Random(150 + seed)
    epochs, final = _history(rng, n_keys=12)

    def build(t):
        return t.windowby(
            t.t, window=tumbling(duration=8), instance=t.k % 3
        ).reduce(
            inst=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            s=pw.reducers.sum(pw.this.v),
        )

    streamed, batch = _both(build, epochs, final)
    assert streamed == batch, (epochs, final)
