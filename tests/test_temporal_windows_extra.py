"""intervals_over windows, window joins across window types, and
sliding/session geometry edge cases (reference ``stdlib/temporal``
``_window.py`` / ``_window_join.py``).
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal import (
    intervals_over,
    session,
    sliding,
    tumbling,
)
from tests.utils import T, run_to_rows


def test_intervals_over_gathers_neighborhoods():
    """Each probe time gathers the data points within its interval."""
    pw.G.clear()
    data = T(
        """
    t  | v
    1  | 1
    4  | 2
    6  | 4
    12 | 8
    """
    )
    probes = T(
        """
    at
    5
    10
    """
    )
    w = data.windowby(
        data.t,
        window=intervals_over(
            at=probes.at, lower_bound=-4, upper_bound=4, is_outer=False
        ),
    ).reduce(
        vals=pw.reducers.sorted_tuple(pw.this.v),
    )
    w = w.select(at=pw.this._pw_window_start, vals=pw.this.vals)
    rows = dict(run_to_rows(w))
    assert rows[5] == (1, 2, 4)   # t in [1, 9]
    assert rows[10] == (4, 8)     # t in [6, 14]


def test_intervals_over_outer_keeps_empty_probes():
    pw.G.clear()
    data = T(
        """
    t | v
    1 | 1
    """
    )
    probes = T(
        """
    at
    100
    """
    )
    w = data.windowby(
        data.t,
        window=intervals_over(
            at=probes.at, lower_bound=-1, upper_bound=1, is_outer=True
        ),
    ).reduce(
        n=pw.reducers.count(),
    )
    w = w.select(at=pw.this._pw_window_start, n=pw.this.n)
    rows = dict(run_to_rows(w))
    # outer: the probe with no data still appears (count of nothing)
    assert 100 in rows


def test_window_join_inner_pairs_same_window():
    pw.G.clear()
    a = T(
        """
    t | va
    1 | 1
    11 | 2
    """
    )
    b = T(
        """
    t | vb
    2 | 10
    3 | 20
    12 | 30
    """
    )
    j = a.window_join(b, a.t, b.t, tumbling(duration=10)).select(
        va=pw.left.va, vb=pw.right.vb
    )
    got = sorted(run_to_rows(j))
    # window [0,10): a(1) x b(10), a(1) x b(20); window [10,20): a(2) x b(30)
    assert got == [(1, 10), (1, 20), (2, 30)]


def test_window_join_left_keeps_unmatched_windows():
    pw.G.clear()
    a = T(
        """
    t  | va
    1  | 1
    25 | 9
    """
    )
    b = T(
        """
    t | vb
    2 | 10
    """
    )
    j = a.window_join_left(b, a.t, b.t, tumbling(duration=10)).select(
        va=pw.left.va, vb=pw.right.vb
    )
    got = sorted(run_to_rows(j), key=repr)
    assert (1, 10) in got
    assert (9, None) in got


def test_sliding_window_geometry_counts():
    """Every point lands in exactly duration/hop windows."""
    pw.G.clear()
    t = T(
        """
    t  | v
    7  | 1
    23 | 1
    """
    )
    w = t.windowby(t.t, window=sliding(hop=5, duration=15)).reduce(
        start=pw.this._pw_window_start,
        n=pw.reducers.count(),
    )
    rows = run_to_rows(w.select(w.start, w.n))
    # 15/5 = 3 windows per point
    assert sum(n for _s, n in rows) == 6
    # the windows covering t=7 start at -5, 0, 5
    assert {s for s, _n in rows if _n and s <= 7} >= {-5, 0, 5}


def test_session_window_merges_across_gap_boundary():
    pw.G.clear()
    t = T(
        """
    t  | v
    1  | 1
    4  | 2
    9  | 4
    30 | 8
    """
    )
    w = t.windowby(t.t, window=session(max_gap=5)).reduce(
        lo=pw.reducers.min(pw.this.t),
        hi=pw.reducers.max(pw.this.t),
        s=pw.reducers.sum(pw.this.v),
    )
    rows = sorted(run_to_rows(w.select(w.lo, w.hi, w.s)))
    assert rows == [(1, 9, 7), (30, 30, 8)]


def test_table_viz_renders_html():
    pw.G.clear()
    t = T(
        """
    a | b
    1 | x
    2 | y
    """
    )
    from pathway_tpu.stdlib.viz import table_viz

    panel = table_viz(t)
    assert "<table>" in panel._repr_html_()  # header renders pre-run
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    html = panel._repr_html_()
    assert "x" in html and "y" in html


def test_intervals_over_behavior_cutoff_applies():
    """behavior= on intervals_over was silently ignored (review finding);
    a cutoff anchored at the BAND end (p + upper_bound) must drop late
    rows once in-band traffic advances the watermark past it.  (The
    watermark advances on ASSIGNED rows: out-of-band traffic does not
    close probe windows.)"""
    from pathway_tpu.stdlib.temporal import common_behavior

    pw.G.clear()
    data = pw.debug.table_from_markdown(
        """
    t  | v  | __time__ | __diff__
    1  | 10 | 2        | 1
    20 | 5  | 4        | 1
    2  | 90 | 6        | 1
    """
    )
    probes = T(
        """
    at
    2
    20
    """
    )
    # two probes: the t=20 row (probe-2's band) advances the watermark
    # to 20, past probe-1's band end 4, so the late t=2 arrival drops
    # from probe 1 — while probe 2's own row stays.  With the pre-fix
    # probe-POINT anchoring the expiry sat at 2 and the fix at 4; either
    # way the late row must drop, and crucially every IN-BAND row ahead
    # of the probe point (t in (p, p+upper]) stays countable
    w = data.windowby(
        data.t,
        window=intervals_over(
            at=probes.at, lower_bound=-2, upper_bound=2, is_outer=False
        ),
        behavior=common_behavior(cutoff=0),
    ).reduce(
        s=pw.reducers.sum(pw.this.v),
    )
    rows = sorted(r[0] for r in run_to_rows(w.select(pw.this.s)))
    assert rows == [5, 10]  # late 90 dropped; both windows intact
