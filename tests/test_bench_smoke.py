"""``bench.py --smoke`` is the benchmark driver's own CI check: a
seconds-long run over a tiny corpus that exercises the host-plane
sections (including the multi-process exchange probe) end to end and
must emit the driver contract — the LAST stdout line is one JSON object.
Keeps the committed BENCH numbers honest: if the driver rots, this fails
in tier-1 instead of at artifact-refresh time.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_emits_wellformed_metrics():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        env=env,
        capture_output=True,
        timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
    assert lines, "no stdout from bench.py --smoke"
    doc = json.loads(lines[-1])  # driver contract: last line is the JSON

    assert doc["smoke"] is True
    assert doc["metric"] == "smoke_wordcount_rows_per_sec"
    assert isinstance(doc["value"], (int, float)) and doc["value"] > 0
    extra = doc["extra"]
    # the pipelined-exchange probe ran: both cluster sizes and the
    # overhead/efficiency keys the README rows trace back to
    for key in (
        "wordcount_rows_per_sec",
        "wordcount_1proc_rows_per_sec",
        "wordcount_multiprocess_rows_per_sec",
        "wordcount_exchange_overhead_pct",
        "wordcount_cpu_normalized_efficiency_2proc",
        "select_rows_per_sec",
        "strdt_rows_per_sec",
    ):
        assert isinstance(extra[key], (int, float)), key
    stats = extra["wordcount_exchange_stats"]
    assert stats["transmissions"] > 0
    assert stats["status_rounds"] > 0
    # the columnar differential ran and its gates held (ISSUE 19: the
    # columnar kernels must beat the row path and the _K_FRAME wire must
    # engage, ship fewer bytes, and burn less codec CPU than the row
    # wire; an assert inside bench_columnar surfaces here as
    # columnar_error)
    assert "columnar_error" not in extra, extra.get("columnar_error")
    assert extra["columnar_rows_per_sec"] >= extra["columnar_row_path_rows_per_sec"]
    assert extra["columnar_speedup_single_core"] >= 1.0
    # the streaming-latency probe ran and its dispersion gate held: a
    # p99/p50 blowout (raised inside bench.py) would surface here as a
    # streaming_latency_error key instead of the smoke summary
    assert "streaming_latency_error" not in extra, extra.get(
        "streaming_latency_error"
    )
    probe = extra["streaming_latency_smoke"]
    assert probe["p50_ms"] > 0
    assert probe["p99_ms"] >= probe["p50_ms"]
    assert probe["dispersion_p99_over_p50"] <= 25.0
    # per-stage breakdown present for the probed rate, with the wakeup
    # pipeline's stages all recording
    (rate_entry,) = extra["streaming_latency_vs_rate"].values()
    stages = rate_entry["stages"]
    for stage in ("ingest", "cut", "process", "sink", "e2e"):
        assert stages[stage]["count"] > 0, stage
        assert stages[stage]["p50_ms"] <= stages[stage]["p99_ms"]
    # the capacity cross-validation ran and held (ISSUE 15: the static
    # estimator's prediction must land within 3x of the sampled operator
    # state on both graphs; a breach raises inside bench.py and would
    # surface here as capacity_error)
    assert "capacity_error" not in extra, extra.get("capacity_error")
    for graph in ("wordcount", "index_churn"):
        ratio = extra[f"capacity_{graph}_ratio"]
        assert 1.0 / 3.0 <= ratio <= 3.0, (graph, ratio)
        assert extra[f"capacity_{graph}_measured_bytes"] > 0, graph
    # the device cross-validation ran and its gates held (ISSUE 20: a
    # warmed serving loop records ZERO steady-state compiles, the
    # shape-unstable control proves the counter is live, and the static
    # sweep predicts no recompile sites; any breach raises inside
    # bench_device and would surface here as device_error)
    assert "device_error" not in extra, extra.get("device_error")
    assert extra["device_steady_state_compiles"] == 0
    assert extra["device_unbucketed_compiles"] > 0
    assert extra["device_predicted_recompile_sites"] == 0
    assert extra["device_warmup_compiles"] < extra["device_unbucketed_compiles"]
    # the tracing-overhead gate ran and held (ISSUE 14: the always-on
    # flight recorder must cost <=2% on both workloads; a gate trip
    # raises inside bench.py and surfaces here as tracing_error)
    assert "tracing_error" not in extra, extra.get("tracing_error")
    assert extra["tracing_overhead_wordcount_pct"] <= 2.0
    assert extra["tracing_overhead_serving_pct"] <= 2.0
    # ...and the attribution block made it into the artifact: serving
    # requests attribute real time to device work
    assert extra["tracing_serving_attribution"].get("device", 0) > 0
