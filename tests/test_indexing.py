"""stdlib.indexing: DataIndex over the external-index engine operator."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import (
    BM25Adapter,
    BruteForceKnnFactory,
    HybridIndexFactory,
    TantivyBM25Factory,
    compile_filter,
)
from tests.utils import T, run_to_rows


def _vec(*xs):
    return tuple(float(x) for x in xs)


def _make_docs():
    return T(
        """
    doc     | vx | vy
    apple   | 1  | 0
    banana  | 0  | 1
    cherry  | 1  | 1
    """
    ).select(
        doc=pw.this.doc,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
    )


def test_knn_query_as_of_now():
    docs = _make_docs()
    queries = T(
        """
    qid | qx | qy
    q1  | 1  | 0
    q2  | 0  | 1
    """
    ).select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy),
    )
    factory = BruteForceKnnFactory(dimensions=2, reserved_space=16)
    index = factory.build_data_index(docs.vec, docs)
    res = index.query_as_of_now(queries.qvec, number_of_matches=2)
    rows = run_to_rows(res)
    by_q = {r[0]: r for r in rows}
    # q1 -> apple then cherry; q2 -> banana then cherry
    assert [d["doc"] for d in by_q["q1"][4]] == ["apple", "cherry"]
    assert [d["doc"] for d in by_q["q2"][4]] == ["banana", "cherry"]
    scores = by_q["q1"][3]
    assert scores[0] == pytest.approx(1.0, abs=1e-5)


def test_knn_query_flattened():
    docs = _make_docs()
    queries = T(
        """
    qid | qx | qy
    q1  | 1  | 0
    """
    ).select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy),
    )
    index = BruteForceKnnFactory(dimensions=2, reserved_space=16).build_data_index(
        docs.vec, docs
    )
    res = index.query_as_of_now(queries.qvec, number_of_matches=2, collapse_rows=False)
    rows = run_to_rows(res)
    assert len(rows) == 2
    docs_returned = [r[4]["doc"] for r in rows]
    assert docs_returned == ["apple", "cherry"]


def test_bm25_index():
    docs = T(
        """
    d | text
    1 | the quick brown fox jumps
    2 | a lazy dog sleeps all day
    3 | the dog chases the fox
    """
    )
    queries = T(
        """
    q
    fox
    dog
    """
    )
    index = TantivyBM25Factory().build_data_index(docs.text, docs)
    res = index.query_as_of_now(queries.q, number_of_matches=2)
    rows = run_to_rows(res)
    by_q = {r[0]: r for r in rows}
    fox_docs = [d["text"] for d in by_q["fox"][3]]
    assert fox_docs and all("fox" in t for t in fox_docs)
    dog_docs = [d["text"] for d in by_q["dog"][3]]
    assert dog_docs and all("dog" in t for t in dog_docs)


def test_hybrid_index_rrf():
    docs = _make_docs()
    queries = T(
        """
    qid | qx | qy
    q1  | 1  | 0
    """
    ).select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy),
    )
    factory = HybridIndexFactory(
        retriever_factories=[
            BruteForceKnnFactory(dimensions=2, reserved_space=16),
            BruteForceKnnFactory(dimensions=2, reserved_space=16, metric="l2sq"),
        ]
    )
    index = factory.build_data_index(docs.vec, docs)
    res = index.query_as_of_now(queries.qvec, number_of_matches=2)
    rows = run_to_rows(res)
    assert [d["doc"] for d in rows[0][4]][0] == "apple"


def test_metadata_filter():
    docs = T(
        """
    doc | vx | vy | owner
    a   | 1  | 0  | alice
    b   | 1  | 0  | bob
    """
    ).select(
        doc=pw.this.doc,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
        meta=pw.apply(lambda o: {"owner": o}, pw.this.owner),
    )
    queries = T(
        """
    qid
    q1
    """
    ).select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda _: (1.0, 0.0), pw.this.qid),
    )
    factory = BruteForceKnnFactory(dimensions=2, reserved_space=16)
    index = factory.build_index(docs.vec, docs, metadata_column=docs.meta)
    from pathway_tpu.stdlib.indexing import DataIndex

    di = DataIndex(docs, index)
    res = di.query_as_of_now(
        queries.qvec, number_of_matches=5, metadata_filter="owner == 'bob'"
    )
    rows = run_to_rows(res)
    assert [d["doc"] for d in rows[0][4]] == ["b"]


def test_query_fully_consistent_updates():
    """query() (non-as-of-now) revises answers when the corpus changes."""
    import threading
    import time as _time

    import pathway_tpu.io.python as pwpy
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    class DocSubject(pwpy.ConnectorSubject):
        def run(self):
            self.next(doc="first", vx=1.0, vy=0.0)
            self.commit()
            _time.sleep(0.3)
            self.next(doc="better", vx=1.0, vy=0.05)
            self.commit()

    class DocsSchema(pw.Schema):
        doc: str
        vx: float
        vy: float

    docs_raw = pwpy.read(DocSubject(), schema=DocsSchema)
    docs = docs_raw.select(
        doc=pw.this.doc,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
    )
    queries = T(
        """
    qid
    q1
    """
    ).select(qid=pw.this.qid, qvec=pw.apply(lambda _: (1.0, 0.05), pw.this.qid))
    index = BruteForceKnnFactory(dimensions=2, reserved_space=16).build_data_index(
        docs.vec, docs
    )
    res = index.query(queries.qvec, number_of_matches=1)
    updates = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: updates.append(
            (is_addition, [d["doc"] for d in row["_pw_index_reply"]])
        ),
    )
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    th = threading.Thread(target=sched.run)
    th.start()
    th.join(timeout=10)
    assert not th.is_alive()
    # the static query is answered first (possibly before any doc arrives),
    # then revised as the corpus streams in: ... -> [first] -> [better]
    assert updates[-1] == (True, ["better"])
    assert (True, ["first"]) in updates
    assert (False, ["first"]) in updates


def test_compile_filter():
    f = compile_filter("owner == 'alice' && size > 10")
    assert f({"owner": "alice", "size": 20})
    assert not f({"owner": "alice", "size": 5})
    assert not f({"owner": "bob", "size": 20})
    g = compile_filter("contains(tags, 'x') || globmatch('*.pdf', path)")
    assert g({"tags": ["x", "y"], "path": "a.txt"})
    assert g({"tags": [], "path": "doc.pdf"})
    assert not g({"tags": [], "path": "doc.txt"})
    h = compile_filter("modified_at >= `100`")
    assert h({"modified_at": 150}) and not h({"modified_at": 50})


def test_bm25_adapter_incremental():
    a = BM25Adapter()
    a.add([(1, "apple pie recipe"), (2, "banana bread recipe")])
    r = a.search(["apple"], [2], [None])
    assert [k for k, _ in r[0]] == [1]
    a.remove([1])
    r = a.search(["apple"], [2], [None])
    assert r[0] == []
    # upsert
    a.add([(2, "apple tart")])
    r = a.search(["apple"], [2], [None])
    assert [k for k, _ in r[0]] == [2]
