"""Differential tests: native expression-VM programs vs the pure-Python
closures in internals/expression.py.

The reference evaluates typed expression trees in Rust
(``src/engine/expression.rs:26-491``); our equivalent is the bytecode VM
in ``native/pathway_native.cpp`` lowered by ``internals/expr_vm.py``.
These tests pin the VM to the closure semantics op by op over an
adversarial value matrix (None, ERROR, bools vs ints, big ints, mixed
types, Json), so any divergence between the two paths fails loudly.
"""

from __future__ import annotations

import math

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.stream import Update
from pathway_tpu.internals import api
from pathway_tpu.internals import expr_vm
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native
from pathway_tpu.internals.json import Json


@pytest.fixture(scope="module")
def native():
    mod = _native.load()
    if mod is None or not hasattr(mod, "vm_compile"):
        pytest.skip("native VM unavailable")
    return mod


class _Table:
    """Stand-in table identity for ColumnReference."""


class _Layout:
    """Minimal layout: columns x,y,z at positions 0,1,2; id is the key."""

    _POS = {"x": 0, "y": 1, "z": 2}

    def resolver(self, ref):
        if ref._name == "id":
            return lambda kv: kv[0]
        pos = self._POS[ref._name]
        return lambda kv, pos=pos: kv[1][pos]

    def resolve_pos(self, ref):
        if ref._name == "id":
            return -1
        return self._POS[ref._name]


_T = _Table()
X = ex.ColumnReference(_T, "x")
Y = ex.ColumnReference(_T, "y")
Z = ex.ColumnReference(_T, "z")
LAYOUT = _Layout()

E = api.ERROR

#: (x, y, z) rows covering the value lattice the closures handle
ROWS = [
    (1, 2, 3),
    (-5, 3, 0),
    (0, 0, 1),
    (2**62, 2**62, 1),          # int64 overflow in + and *
    (2**100, 7, 2),              # big ints -> generic path
    (1.5, 2.5, 0.0),
    (1.5, 0.0, -3.25),
    (float("nan"), 1.0, 2.0),
    (1, 2.5, 3),                 # mixed int/float
    (True, False, True),         # bools are NOT ints for & | ^
    (True, 1, 0),
    ("ab", "cd", "ab"),
    ("ab", 3, None),             # str+int -> ERROR; None ops -> None
    (None, None, 1),
    (None, 5, "x"),
    (E, 1, 2),                   # ERROR propagation
    (1, E, E),
    ((1, 2), (3, 4), 1),         # tuple concat / compare / getitem
    (b"ab", b"cd", 0),
]


def _key(i):
    return K.ref_scalar("vmtest", i)


def _batch():
    return [Update(_key(i), row, 1) for i, row in enumerate(ROWS)]


def _canon(v):
    """Identity-aware canonical form: distinguishes 1/True/1.0, treats
    NaN as equal to itself, keeps ERROR as a sentinel."""
    if v is api.ERROR:
        return "<ERROR>"
    if isinstance(v, tuple):
        return tuple(_canon(x) for x in v)
    if isinstance(v, float) and math.isnan(v):
        return "<nan>"
    return (type(v).__name__, repr(v))


def _assert_parity(native, exprs, *, expect_native=True):
    """Evaluate exprs through the VM and through the closures; compare."""
    progs = expr_vm.lower_programs(list(exprs), LAYOUT)
    if expect_native:
        assert progs is not None, "expected a native lowering"
    if progs is None:
        return
    errors_native: list = []
    out = native.vm_eval_batch(
        _batch(), progs, Update, api.ERROR, errors_native.append
    )
    closures = [e._compile(LAYOUT.resolver) for e in exprs]
    for u_in, u_out in zip(_batch(), out):
        expected = []
        row_raised = False
        for c in closures:
            try:
                expected.append(c((u_in.key, u_in.values)))
            except Exception:
                row_raised = True
                break
        if row_raised:
            expected = [api.ERROR]
        assert u_out.key == u_in.key and u_out.diff == u_in.diff
        got = list(u_out.values)
        assert [_canon(g) for g in got] == [_canon(e) for e in expected], (
            u_in.values,
            got,
            expected,
        )


ALL_BIN_OPS = ["+", "-", "*", "/", "//", "%", "**", "==", "!=", "<", "<=",
               ">", ">=", "&", "|", "^"]


@pytest.mark.parametrize("op", ALL_BIN_OPS)
def test_binary_op_parity(native, op):
    if op == "**":
        # huge-exponent rows would legitimately compute for hours on BOTH
        # paths; pin ** to a bounded matrix instead
        rows = [
            (2, 10, 0), (2, -2, 0), (0, 0, 0), (1.5, 2.0, 0),
            (-2, 3, 0), (None, 2, 0), (E, 2, 0), ("a", 2, 0),
            (True, True, 0),
        ]
        batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
        e = ex.BinaryExpression(op, X, Y)
        progs = expr_vm.lower_programs([e], LAYOUT)
        assert progs is not None
        out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda x: None)
        c = e._compile(LAYOUT.resolver)
        for u_in, u_out in zip(batch, out):
            expected = c((u_in.key, u_in.values))
            assert _canon(u_out.values[0]) == _canon(expected), u_in.values
        return
    _assert_parity(native, [ex.BinaryExpression(op, X, Y)])


def test_unary_parity(native):
    _assert_parity(native, [ex.UnaryExpression("-", X), ex.UnaryExpression("~", X)])


def test_is_none_parity(native):
    _assert_parity(native, [X.is_none(), X.is_not_none()])


def test_if_else_coalesce_require_parity(native):
    _assert_parity(
        native,
        [
            ex.if_else(ex.BinaryExpression(">", X, Y), X, Y),
            ex.coalesce(X, Y, ex.ConstExpression(99)),
            ex.require(X, Y),
            ex.if_else(
                X.is_none(), ex.ConstExpression(-1),
                ex.if_else(ex.BinaryExpression(">", X, ex.ConstExpression(0)), X, Y),
            ),
        ],
    )


def test_cast_parity(native):
    import pathway_tpu.internals.dtype as dt

    _assert_parity(
        native,
        [ex.cast(t, X) for t in (dt.INT, dt.FLOAT, dt.BOOL, dt.STR)],
    )


def test_tuple_get_parity(native):
    _assert_parity(
        native,
        [
            ex.make_tuple(X, Y),
            ex.GetExpression(X, ex.ConstExpression(0), check_if_exists=False),
            ex.GetExpression(
                X, ex.ConstExpression(0),
                default=ex.ConstExpression("dflt"), check_if_exists=True,
            ),
        ],
    )


def test_unwrap_fill_error_parity(native):
    _assert_parity(
        native,
        [
            ex.unwrap(X),
            ex.fill_error(ex.BinaryExpression("/", X, Y), ex.ConstExpression(-1)),
            ex.fill_error(X, Y),
        ],
    )


def test_pointer_parity(native):
    _assert_parity(
        native,
        [
            ex.PointerExpression(_T, X, Y),
            ex.PointerExpression(_T, X, optional=True),
        ],
    )


def test_declare_type_and_const(native):
    import pathway_tpu.internals.dtype as dt

    _assert_parity(
        native,
        [ex.declare_type(dt.ANY, X), ex.ConstExpression("k")],
    )


def test_mixed_native_and_pycall(native):
    """A UDF apply rides CALL_PY inside an otherwise-native program."""
    _assert_parity(
        native,
        [
            ex.BinaryExpression(
                "+",
                ex.apply_with_type(lambda v: (v, v), object, X),
                ex.MakeTupleExpression(Y),
            ),
        ],
        expect_native=True,
    )


def test_raising_udf_contains_row(native):
    """ApplyExpression's closure contains its own exception (error-logged,
    returns ERROR) — the VM must propagate that ERROR through native ops.
    A closure that raises PAST the containment (bare pyfunc) must instead
    trigger the row-level on_error + (ERROR,) path like rowwise_map."""

    def boom(v):
        raise RuntimeError("boom")

    # (a) apply: contained inside the closure -> column is ERROR, no
    # row-level on_error
    e = ex.apply_with_type(boom, int, X)
    prog = expr_vm.lower_programs(
        [ex.BinaryExpression("+", e, ex.ConstExpression(1))], LAYOUT
    )
    assert prog is not None
    logged: list = []
    out = native.vm_eval_batch(
        _batch()[:3], prog, Update, api.ERROR, logged.append
    )
    assert all(u.values == (api.ERROR,) for u in out)
    assert logged == []

    # (b) a raw raising pyfunc (no Apply containment): row-level
    # containment fires exactly like rowwise_map
    raw = native.vm_compile(
        [expr_vm.OP_CALL_PY, 0], (), (lambda kv: (_ for _ in ()).throw(RuntimeError("x")),)
    )
    out2 = native.vm_eval_batch(
        _batch()[:2], (raw,), Update, api.ERROR, logged.append
    )
    assert all(u.values == (api.ERROR,) for u in out2)
    assert len(logged) == 2 and all(isinstance(x, RuntimeError) for x in logged)


def test_json_get_convert_parity(native):
    rows = [
        (Json({"a": 1, "b": [10, 20]}), "a", 1),
        (Json({"a": "s"}), "a", 0),
        (Json({"a": None}), "a", 0),
        (Json(3.5), "q", 0),
        (Json(True), "q", 0),
        (None, "a", 0),
        (E, "a", 0),
    ]
    batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
    import pathway_tpu.internals.dtype as dt

    exprs = [
        ex.GetExpression(X, Y, check_if_exists=False),
        ex.GetExpression(
            X, Y, default=ex.ConstExpression(None), check_if_exists=True
        ),
        ex.ConvertExpression(
            dt.INT,
            ex.GetExpression(
                X, ex.ConstExpression("a"),
                default=ex.ConstExpression(None), check_if_exists=True,
            ),
        ),
        ex.ConvertExpression(dt.FLOAT, X),
        ex.ConvertExpression(dt.BOOL, X, unwrap=True),
    ]
    progs = expr_vm.lower_programs(exprs, LAYOUT)
    assert progs is not None
    out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda e: None)
    closures = [e._compile(LAYOUT.resolver) for e in exprs]
    for u_in, u_out in zip(batch, out):
        expected = [c((u_in.key, u_in.values)) for c in closures]
        assert [_canon(g) for g in u_out.values] == [
            _canon(e) for e in expected
        ], (u_in.values, list(u_out.values), expected)


def test_filter_parity(native):
    preds = [
        ex.BinaryExpression(">", X, Y),
        X.is_none(),
        ex.BinaryExpression("==", X, X),
        ex.BinaryExpression("/", ex.ConstExpression(1), X),  # 1/x truthiness
    ]
    for pred in preds:
        prog = expr_vm.lower_program(pred, LAYOUT)
        assert prog is not None
        out = native.vm_filter_batch(_batch(), prog, api.ERROR)
        c = pred._compile(LAYOUT.resolver)
        expected = []
        for u in _batch():
            try:
                keep = c((u.key, u.values))
            except Exception:
                continue
            if keep is not None and keep is not api.ERROR and bool(keep):
                expected.append(u)
        assert [u.key for u in out] == [u.key for u in expected], pred


def test_vm_rejects_malformed_programs(native):
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_JUMP, 999], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_LOAD_CONST, 5], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_CALL_PY, 0], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([99], (), ())
    # stack discipline: underflow and wrong exit depth must be rejected
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_BIN, 0], (), ())  # pops empty stack
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_POP], (), ())
    with pytest.raises(ValueError):
        native.vm_compile(
            [expr_vm.OP_LOAD_KEY, expr_vm.OP_LOAD_KEY], (), ()
        )  # exits with depth 2
    with pytest.raises(ValueError):
        native.vm_compile(
            [expr_vm.OP_LOAD_KEY, expr_vm.OP_MAKE_TUPLE, 2], (), ()
        )  # MAKE_TUPLE deeper than stack
    with pytest.raises(ValueError):
        # jump into the middle of an instruction's operands
        native.vm_compile([expr_vm.OP_JUMP, 3, expr_vm.OP_LOAD_COL, 0], (), ())


def test_end_to_end_pipeline_matches_disable_native():
    """The same select/filter pipeline prints identically with the VM and
    with PATHWAY_DISABLE_NATIVE=1 (subprocess)."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_markdown('''\n"
        "a | b | s\n"
        "1 | 2 | x\n"
        "3 | 0 | y\n"
        "5 | 4 | z\n"
        "''')\n"
        "out = t.select(t.a, q=t.a * 2 + t.b, d=t.a / t.b,\n"
        "    w=pw.if_else(t.a > 2, t.s, pw.coalesce(t.s, 'n')),\n"
        "    p=t.pointer_from(t.a), m=pw.make_tuple(t.a, t.b)[1])\n"
        "out = out.filter(out.q > 3)\n"
        "pw.debug.compute_and_print(out, include_id=False)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    a = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    env2 = dict(env, PATHWAY_DISABLE_NATIVE="1")
    b = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env2
    )
    assert a.returncode == 0, a.stderr
    assert b.returncode == 0, b.stderr
    assert a.stdout == b.stdout


# ---------------------------------------------------------------------------
# .str / .dt / .num namespace methods: OP_METHOD native implementations vs
# the closure lambdas (reference evaluates these enums in Rust,
# src/engine/expression.rs:26-340)


def _assert_parity_rows(native, exprs, rows, *, expect_native=True):
    """_assert_parity over a custom row matrix."""
    batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
    progs = expr_vm.lower_programs(list(exprs), LAYOUT)
    if expect_native:
        assert progs is not None, "expected a native lowering"
    if progs is None:
        return
    out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda x: None)
    closures = [e._compile(LAYOUT.resolver) for e in exprs]
    for u_in, u_out in zip(batch, out):
        expected = []
        row_raised = False
        for c in closures:
            try:
                expected.append(c((u_in.key, u_in.values)))
            except Exception:
                row_raised = True
                break
        if row_raised:
            expected = [api.ERROR]
        got = list(u_out.values)
        assert [_canon(g) for g in got] == [_canon(e) for e in expected], (
            u_in.values,
            got,
            expected,
        )


_STR_ROWS = [
    ("  Hello World  ", "l", 0),
    ("csv,data,123", ",", 0),
    ("", "", 0),
    ("ÜniCödé Στρ", "ö", 0),          # non-ASCII: Unicode fallback paths
    ("MiXeD cAsE", "c", 0),
    ("don't stop", "o", 0),           # title() apostrophe rule
    ("aaa", "aa", 0),                 # overlapping count
    ("\t spaced \n", " ", 0),
    ("x" * 300, "x", 0),
    (None, "a", 0),                   # propagate_none
    (E, "a", 0),                      # propagate ERROR
    (123, "a", 0),                    # non-str -> closure raises -> ERROR
]


def test_method_str_simple_parity(native):
    exprs = [
        X.str.lower(), X.str.upper(), X.str.swapcase(), X.str.title(),
        X.str.reversed(), X.str.len(), X.str.strip(), X.str.lstrip(),
        X.str.rstrip(), X.str.strip(" dH\t\n"), X.str.lstrip("x"),
        X.str.rstrip("  "),
    ]
    _assert_parity_rows(native, exprs, _STR_ROWS)


def test_method_str_search_parity(native):
    exprs = [
        X.str.count("a"), X.str.count(""), X.str.find("o"),
        X.str.find("o", 3), X.str.find("o", 1, 9), X.str.find("o", -4),
        X.str.rfind("o"), X.str.rfind("o", 2, -1),
        X.str.startswith("  H"), X.str.endswith("  "),
        X.str.startswith(""), X.str.replace("a", "A"),
        X.str.replace("a", "A", 1), X.str.slice(2, 7),
        X.str.slice(-5, -1), X.str.slice(4, 2), X.str.slice(0, 10**30),
    ]
    _assert_parity_rows(native, exprs, _STR_ROWS)


def test_method_str_parse_parity(native):
    rows = [
        ("42", 0, 0), ("  -17  ", 0, 0), ("3.5", 0, 0), ("1_000", 0, 0),
        ("0x1f", 0, 0), ("", 0, 0), ("inf", 0, 0), ("-2.5e3", 0, 0),
        ("nan", 0, 0), ("yes", 0, 0), ("NO", 0, 0), ("True", 0, 0),
        ("on", 0, 0), ("junk", 0, 0), ("2" * 40, 0, 0),
        (None, 0, 0), (E, 0, 0),
    ]
    exprs = [
        X.str.parse_int(), X.str.parse_int(optional=True),
        X.str.parse_float(), X.str.parse_float(optional=True),
        X.str.parse_bool(optional=True),
        X.str.parse_bool(true_values=("yes",), false_values=("no",),
                         optional=True),
    ]
    _assert_parity_rows(native, exprs, rows)
    # non-optional parse_bool raises per row -> whole-row ERROR parity
    _assert_parity_rows(native, [X.str.parse_bool()], rows)


_FMTS = [
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%dT%H:%M:%S.%f",
    "%d/%m/%y %H:%M",
    "%Y%m%d%H%M%S",
    "%I:%M %p",
    "%Y-%m-%d %H:%M:%S %z",
    "%Y-%j",
    "%d %b %Y",        # %b: month names -> Python strptime fallback
]


def test_method_strptime_parity(native):
    samples = [
        "2020-03-04 10:20:30", "2020-03-04T10:20:30.123456",
        "2020-03-04T10:20:30.5", "04/03/99 23:59", "04/03/69 00:00",
        "20200304102030", "11:30 PM", "11:30 am", "12:01 AM",
        "2020-03-04 10:20:30 +0530", "2020-03-04 10:20:30 Z",
        "2020-03-04 10:20:30 -07:00", "2020-03-04 10:20:30 +053015",
        "2020-366", "2020-060", "2019-365", "04 Mar 2020",
        "not a date", "2020-13-04 10:20:30", "2020-03-04", "",
    ]
    rows = [(s, 0, 0) for s in samples] + [(None, 0, 0), (E, 0, 0)]
    for fmt in _FMTS:
        _assert_parity_rows(
            native, [X.str.parse_datetime(fmt), X.dt.strptime(fmt)], rows
        )


def test_method_strftime_parity(native):
    import datetime as dtm

    from pathway_tpu.internals.dtype import DateTimeNaive, DateTimeUtc

    rows = [
        (DateTimeNaive(2020, 3, 4, 10, 20, 30, 123456), 0, 0),
        (DateTimeNaive(1969, 12, 31, 23, 59, 59), 0, 0),
        (DateTimeNaive(50, 1, 2), 0, 0),            # no %Y zero-pad (glibc)
        (DateTimeNaive(2024, 2, 29, 0, 0, 1), 0, 0),
        (dtm.datetime(2020, 3, 4, 13, 1, 2, tzinfo=dtm.timezone.utc), 0, 0),
        (None, 0, 0),
        (E, 0, 0),
    ]
    for fmt in ["%Y-%m-%d %H:%M:%S", "%y/%j %I%p", "%H:%M:%S.%f", "%% %d",
                "%A %d %B"]:  # %A/%B -> Python fallback
        _assert_parity_rows(native, [X.dt.strftime(fmt)], rows)


def test_method_dt_fields_parity(native):
    import datetime as dtm

    from pathway_tpu.internals.dtype import DateTimeNaive, DateTimeUtc

    rows = [
        (DateTimeNaive(2020, 3, 4, 10, 20, 30, 123456), 0, 0),
        (DateTimeNaive(1969, 12, 31, 23, 59, 59, 999999), 0, 0),
        (DateTimeNaive(2024, 2, 29), 0, 0),
        (DateTimeNaive(2024, 12, 31), 0, 0),
        (DateTimeNaive(1, 1, 1), 0, 0),
        (dtm.datetime(2020, 1, 1, tzinfo=dtm.timezone.utc), 0, 0),
        (DateTimeUtc(2021, 6, 15, 12, tzinfo=dtm.timezone.utc), 0, 0),
        (None, 0, 0),
        (E, 0, 0),
        ("not a date", 0, 0),
    ]
    exprs = [
        X.dt.nanosecond(), X.dt.microsecond(), X.dt.millisecond(),
        X.dt.second(), X.dt.minute(), X.dt.hour(), X.dt.day(),
        X.dt.month(), X.dt.year(), X.dt.day_of_week(), X.dt.day_of_year(),
    ]
    _assert_parity_rows(native, exprs, rows)
    for unit in ("s", "ms", "us", "ns"):
        _assert_parity_rows(native, [X.dt.timestamp(unit=unit)], rows)


def test_method_dt_round_floor_parity(native):
    import datetime as dtm
    from zoneinfo import ZoneInfo

    from pathway_tpu.internals.dtype import DateTimeNaive, Duration

    rows = [
        (DateTimeNaive(2020, 3, 4, 10, 20, 30, 123456), Duration(minutes=15), 0),
        (DateTimeNaive(2020, 3, 4, 10, 7, 30), Duration(minutes=15), 0),
        (DateTimeNaive(1969, 12, 31, 23, 59, 59), Duration(hours=1), 0),
        (DateTimeNaive(2020, 3, 4, 10, 20, 30), Duration(seconds=7), 0),
        (DateTimeNaive(2020, 3, 4), Duration(days=1), 0),
        (DateTimeNaive(2020, 3, 4, 12), Duration(days=1), 0),  # .5 ties
        (DateTimeNaive(2020, 3, 5, 12), Duration(days=1), 0),
        (dtm.datetime(2020, 3, 4, 10, 20, tzinfo=dtm.timezone.utc),
         Duration(minutes=30), 0),
        (dtm.datetime(2020, 3, 8, 2, 30,
                      tzinfo=ZoneInfo("America/New_York")),
         Duration(hours=1), 0),                       # DST-gap wall time
        (DateTimeNaive(2020, 3, 4), Duration(0), 0),  # zero step -> ERROR
        (None, Duration(minutes=1), 0),
        (E, Duration(minutes=1), 0),
    ]
    _assert_parity_rows(native, [X.dt.round(Y), X.dt.floor(Y)], rows)


def test_method_duration_parity(native):
    from pathway_tpu.internals.dtype import Duration

    rows = [
        (Duration(days=3, hours=5, minutes=7, seconds=11, microseconds=13), 0, 0),
        (Duration(days=-3, hours=-5), 0, 0),
        (Duration(0), 0, 0),
        (Duration(microseconds=1), 0, 0),
        (Duration(days=10**5), 0, 0),
        (Duration(weeks=-1, days=3), 0, 0),
        (None, 0, 0),
        (E, 0, 0),
        (5, 0, 0),  # non-duration -> closure raises -> ERROR
    ]
    exprs = [
        X.dt.nanoseconds(), X.dt.microseconds(), X.dt.milliseconds(),
        X.dt.seconds(), X.dt.minutes(), X.dt.hours(), X.dt.days(),
        X.dt.weeks(),
    ]
    _assert_parity_rows(native, exprs, rows)


def test_method_num_parity(native):
    rows = [
        (5, 3, 0), (-5, 0, 0), (2.5, 1, 0), (-2.5, 2, 0),
        (float("nan"), 9, 0), (float("-inf"), 0, 0), (2**100, 0, 0),
        (-(2**100), 0, 0), (True, 0, 0), (None, 7, 0), (E, 7, 0),
        ("x", 0, 0),
    ]
    _assert_parity_rows(
        native,
        [X.num.abs(), X.num.fill_na(-1), X.num.fill_na(Y)],
        rows,
    )


def test_method_num_round_parity(native):
    rows = [
        (2.5, 0, 0), (3.5, 0, 0), (-2.5, 0, 0), (2.675, 2, 0),
        (1.0005, 3, 0), (-0.5, 0, 0), (0.0, 0, 0),
        (float("nan"), 0, 0), (float("inf"), 1, 0), (float("-inf"), 0, 0),
        (123456.789, -2, 0), (5, 0, 0), (-7, 3, 0), (2**100, 2, 0),
        (12345, -2, 0),                # negative ndigits on an int
        (True, 0, 0),                  # bool: int.__round__ keeps int
        (None, 0, 0), (E, 0, 0),
        ("x", 0, 0),                   # non-numeric -> ERROR
        (1.5, True, 0),                # bool ndigits is a valid int
        (1.5, None, 0),                # round(x, None) -> int, both paths
        (1.5, 2.0, 0),                 # float ndigits -> ERROR
        (7, 2**70, 0), (1.5, 2**70, 0),  # ndigits beyond C long
    ]
    exprs = [
        X.num.round(), X.num.round(0), X.num.round(1), X.num.round(2),
        X.num.round(-1), X.num.round(-2), X.num.round(Y),
    ]
    _assert_parity_rows(native, exprs, rows)


def test_method_str_split_parity(native):
    rows = [
        ("a b  c", " ", 0),
        ("  lead and trail  ", " ", 1),
        ("csv,data,,123", ",", 2),
        ("", ",", 0),
        ("one", "::", 5),
        ("a::b::c::d", "::", 2),
        ("tab\tnew\nline mix", ",", 0),
        ("ÜniCödé Στρ x", "ö", 1),      # non-ASCII text and separator
        ("x" * 50 + " " + "y" * 50, "x", 0),
        ("a,b", "", 0),                  # empty sep -> ValueError -> ERROR
        ("a b", ",", -1),
        (None, ",", 0),
        (E, ",", 0),
        (123, ",", 0),                   # non-str subject -> ERROR
        ("a b", 7, 0),                   # non-str sep -> ERROR
        ("a b", ",", None),              # non-int maxsplit -> ERROR
        ("a b", ",", True),              # bool maxsplit is a valid int
        ("a,b c,d", ",", 2**70),         # maxsplit beyond ssize_t: ERROR
    ]
    exprs = [
        X.str.split(), X.str.split(None, 1), X.str.split(" "),
        X.str.split(",", 1), X.str.split(Y), X.str.split(Y, Z),
    ]
    _assert_parity_rows(native, exprs, rows)


def test_method_fallbacks_still_lower(native):
    """Expressions outside the native set (user UDFs via apply) embed as
    CALL_PY but the program still compiles (mixed native + fallback in
    one select)."""
    rows = [(3.0, 2.0, 0), (None, 1.0, 0), (E, 4.0, 0)]
    exprs = [
        ex.ApplyExpression(lambda v: (v or 0.0) * 10.0, float, (X,), {}),
        Y.num.round(1),
    ]
    _assert_parity_rows(native, exprs, rows, expect_native=False)


def _tz_rows():
    """Adversarial datetimes for tz lowering: DST gap/fold (both folds),
    far past/future (rule-footer fallback), month/year edges, aware
    inputs.  Plain ``datetime`` rows: the schema-annotation subclasses
    survive ``replace``/``astimezone`` on the closure path but the native
    constructor builds the base type — the VALUES must match."""
    import datetime as dtm
    from zoneinfo import ZoneInfo

    d = dtm.datetime
    return [
        (d(2021, 7, 1, 12, 0, 0, tzinfo=dtm.timezone.utc), 0, 0),
        (d(2021, 7, 1, 12, 0, 0, tzinfo=ZoneInfo("Asia/Tokyo")), 0, 0),
        (d(2020, 3, 4, 10, 20, 30, 123456), 0, 0),
        (d(2024, 3, 10, 2, 30, 0), 0, 0),     # US spring-forward gap
        (d(2024, 11, 3, 1, 30, 0), 0, 0),     # US fall-back fold=0
        (d(2024, 11, 3, 1, 30, 0, fold=1), 0, 0),  # ... fold=1
        (d(2024, 3, 31, 2, 30, 0), 0, 0),     # EU spring-forward gap
        (d(2024, 10, 27, 2, 30, 0, fold=1), 0, 0),  # EU fall-back
        (d(1900, 1, 1, 0, 0, 0), 0, 0),       # before first transition
        (d(2090, 6, 15, 12, 0, 0), 0, 0),     # past last: rule footer
        (d(1969, 12, 31, 23, 59, 59), 0, 0),
        (d(2000, 2, 29, 23, 59, 59, 999999), 0, 0),
        (None, 0, 0),
        (E, 0, 0),
        ("not a datetime", 0, 0),
    ]


@pytest.mark.parametrize(
    "tz",
    [
        "America/New_York",
        "Europe/Paris",
        "Asia/Tokyo",
        "Australia/Lord_Howe",  # 30-minute DST shift
        "UTC",
    ],
)
def test_method_tz_convert_parity(native, tz):
    """dt.to_utc / dt.to_naive_in_timezone lower natively (packed
    transition tables) and match the ZoneInfo closures row by row."""
    exprs = [X.dt.to_utc(tz), X.dt.to_naive_in_timezone(tz)]
    _assert_parity_rows(native, exprs, _tz_rows())


def test_method_tz_convert_unknown_zone_falls_back(native):
    """An unpackable zone still lowers (sentinel table -> per-value
    Python fallback inside the native method) and errors identically."""
    exprs = [
        X.dt.to_utc("No/Such_Zone"),
        X.dt.to_naive_in_timezone("No/Such_Zone"),
    ]
    _assert_parity_rows(native, exprs, _tz_rows())


def test_method_from_timestamp_parity(native):
    rows = [
        (0, 0, 0),
        (1, 0, 0),
        (-1, 0, 0),
        (1700000000, 0, 0),
        (1700000000.123456, 0, 0),
        (-62135596800, 0, 0),            # year 1 boundary
        (253402300799.999, 0, 0),        # year 9999 tail
        (253402300801.0, 0, 0),          # out of range -> ERROR
        (2.5e-06, 0, 0),                 # microsecond rounding (half-even)
        (3.5e-06, 0, 0),
        (float("nan"), 0, 0),            # -> ERROR
        (float("inf"), 0, 0),            # -> ERROR
        (2**70, 0, 0),                   # -> ERROR (overflow)
        (None, 0, 0),
        (E, 0, 0),
        ("x", 0, 0),                     # non-numeric -> ERROR
        (True, 0, 0),                    # bool is a valid number
    ]
    for unit in ("s", "ms", "us", "ns"):
        exprs = [
            X.dt.from_timestamp(unit),
            X.dt.utc_from_timestamp(unit),
        ]
        _assert_parity_rows(native, exprs, rows)


def test_tz_pipeline_compiles_without_call_py(native):
    """Satellite: a strptime -> tz-convert -> format pipeline must lower
    to a program with NO CALL_PY ops (the whole chain runs natively)."""
    exprs = [
        X.str.parse_datetime("%Y-%m-%d %H:%M:%S")
        .dt.to_utc("Europe/Paris")
        .dt.to_naive_in_timezone("Asia/Tokyo")
        .dt.strftime("%Y-%m-%dT%H:%M:%S"),
        Y.dt.from_timestamp("ms").dt.strftime("%H:%M:%S"),
    ]
    for e in exprs:
        asm = expr_vm._Asm(LAYOUT)
        expr_vm._lower(e, asm)
        ops = [asm.code[i] for i in range(0, len(asm.code), 2)]
        assert expr_vm.OP_CALL_PY not in ops, "program contains CALL_PY"
        assert not asm.pyfuncs, "program embeds a Python fallback closure"
    progs = expr_vm.lower_programs(exprs, LAYOUT)
    assert progs is not None, "pipeline must lower natively"
    rows = [
        ("2024-03-31 02:30:00", 1700000000123, 0),
        ("2020-01-01 00:00:00", 0, 0),
        ("not a date", -1, 0),
        (None, None, 0),
    ]
    _assert_parity_rows(native, exprs, rows)


def test_method_strptime_matches_python_over_format_grid(native):
    """Round-trip grid: strftime(fmt) then strptime(fmt) through BOTH
    paths over a set of datetimes x formats."""
    import datetime as dtm

    base = [
        dtm.datetime(2020, 3, 4, 10, 20, 30, 123456),
        dtm.datetime(1999, 12, 31, 23, 59, 59),
        dtm.datetime(2024, 2, 29, 0, 0, 1),
        dtm.datetime(1970, 1, 1),
    ]
    for fmt in ["%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S.%f",
                "%d/%m/%Y %I:%M %p", "%Y%m%d %H%M%S"]:
        rows = [(d.strftime(fmt), 0, 0) for d in base]
        _assert_parity_rows(native, [X.str.parse_datetime(fmt)], rows)
        # and the parsed values are the true datetimes
        batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
        progs = expr_vm.lower_programs([X.str.parse_datetime(fmt)], LAYOUT)
        out = native.vm_eval_batch(batch, progs, Update, api.ERROR,
                                   lambda x: None)
        for d, u in zip(base, out):
            expected = dtm.datetime.strptime(d.strftime(fmt), fmt)
            assert u.values[0] == expected
