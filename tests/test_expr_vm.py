"""Differential tests: native expression-VM programs vs the pure-Python
closures in internals/expression.py.

The reference evaluates typed expression trees in Rust
(``src/engine/expression.rs:26-491``); our equivalent is the bytecode VM
in ``native/pathway_native.cpp`` lowered by ``internals/expr_vm.py``.
These tests pin the VM to the closure semantics op by op over an
adversarial value matrix (None, ERROR, bools vs ints, big ints, mixed
types, Json), so any divergence between the two paths fails loudly.
"""

from __future__ import annotations

import math

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.stream import Update
from pathway_tpu.internals import api
from pathway_tpu.internals import expr_vm
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native
from pathway_tpu.internals.json import Json


@pytest.fixture(scope="module")
def native():
    mod = _native.load()
    if mod is None or not hasattr(mod, "vm_compile"):
        pytest.skip("native VM unavailable")
    return mod


class _Table:
    """Stand-in table identity for ColumnReference."""


class _Layout:
    """Minimal layout: columns x,y,z at positions 0,1,2; id is the key."""

    _POS = {"x": 0, "y": 1, "z": 2}

    def resolver(self, ref):
        if ref._name == "id":
            return lambda kv: kv[0]
        pos = self._POS[ref._name]
        return lambda kv, pos=pos: kv[1][pos]

    def resolve_pos(self, ref):
        if ref._name == "id":
            return -1
        return self._POS[ref._name]


_T = _Table()
X = ex.ColumnReference(_T, "x")
Y = ex.ColumnReference(_T, "y")
Z = ex.ColumnReference(_T, "z")
LAYOUT = _Layout()

E = api.ERROR

#: (x, y, z) rows covering the value lattice the closures handle
ROWS = [
    (1, 2, 3),
    (-5, 3, 0),
    (0, 0, 1),
    (2**62, 2**62, 1),          # int64 overflow in + and *
    (2**100, 7, 2),              # big ints -> generic path
    (1.5, 2.5, 0.0),
    (1.5, 0.0, -3.25),
    (float("nan"), 1.0, 2.0),
    (1, 2.5, 3),                 # mixed int/float
    (True, False, True),         # bools are NOT ints for & | ^
    (True, 1, 0),
    ("ab", "cd", "ab"),
    ("ab", 3, None),             # str+int -> ERROR; None ops -> None
    (None, None, 1),
    (None, 5, "x"),
    (E, 1, 2),                   # ERROR propagation
    (1, E, E),
    ((1, 2), (3, 4), 1),         # tuple concat / compare / getitem
    (b"ab", b"cd", 0),
]


def _key(i):
    return K.ref_scalar("vmtest", i)


def _batch():
    return [Update(_key(i), row, 1) for i, row in enumerate(ROWS)]


def _canon(v):
    """Identity-aware canonical form: distinguishes 1/True/1.0, treats
    NaN as equal to itself, keeps ERROR as a sentinel."""
    if v is api.ERROR:
        return "<ERROR>"
    if isinstance(v, tuple):
        return tuple(_canon(x) for x in v)
    if isinstance(v, float) and math.isnan(v):
        return "<nan>"
    return (type(v).__name__, repr(v))


def _assert_parity(native, exprs, *, expect_native=True):
    """Evaluate exprs through the VM and through the closures; compare."""
    progs = expr_vm.lower_programs(list(exprs), LAYOUT)
    if expect_native:
        assert progs is not None, "expected a native lowering"
    if progs is None:
        return
    errors_native: list = []
    out = native.vm_eval_batch(
        _batch(), progs, Update, api.ERROR, errors_native.append
    )
    closures = [e._compile(LAYOUT.resolver) for e in exprs]
    for u_in, u_out in zip(_batch(), out):
        expected = []
        row_raised = False
        for c in closures:
            try:
                expected.append(c((u_in.key, u_in.values)))
            except Exception:
                row_raised = True
                break
        if row_raised:
            expected = [api.ERROR]
        assert u_out.key == u_in.key and u_out.diff == u_in.diff
        got = list(u_out.values)
        assert [_canon(g) for g in got] == [_canon(e) for e in expected], (
            u_in.values,
            got,
            expected,
        )


ALL_BIN_OPS = ["+", "-", "*", "/", "//", "%", "**", "==", "!=", "<", "<=",
               ">", ">=", "&", "|", "^"]


@pytest.mark.parametrize("op", ALL_BIN_OPS)
def test_binary_op_parity(native, op):
    if op == "**":
        # huge-exponent rows would legitimately compute for hours on BOTH
        # paths; pin ** to a bounded matrix instead
        rows = [
            (2, 10, 0), (2, -2, 0), (0, 0, 0), (1.5, 2.0, 0),
            (-2, 3, 0), (None, 2, 0), (E, 2, 0), ("a", 2, 0),
            (True, True, 0),
        ]
        batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
        e = ex.BinaryExpression(op, X, Y)
        progs = expr_vm.lower_programs([e], LAYOUT)
        assert progs is not None
        out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda x: None)
        c = e._compile(LAYOUT.resolver)
        for u_in, u_out in zip(batch, out):
            expected = c((u_in.key, u_in.values))
            assert _canon(u_out.values[0]) == _canon(expected), u_in.values
        return
    _assert_parity(native, [ex.BinaryExpression(op, X, Y)])


def test_unary_parity(native):
    _assert_parity(native, [ex.UnaryExpression("-", X), ex.UnaryExpression("~", X)])


def test_is_none_parity(native):
    _assert_parity(native, [X.is_none(), X.is_not_none()])


def test_if_else_coalesce_require_parity(native):
    _assert_parity(
        native,
        [
            ex.if_else(ex.BinaryExpression(">", X, Y), X, Y),
            ex.coalesce(X, Y, ex.ConstExpression(99)),
            ex.require(X, Y),
            ex.if_else(
                X.is_none(), ex.ConstExpression(-1),
                ex.if_else(ex.BinaryExpression(">", X, ex.ConstExpression(0)), X, Y),
            ),
        ],
    )


def test_cast_parity(native):
    import pathway_tpu.internals.dtype as dt

    _assert_parity(
        native,
        [ex.cast(t, X) for t in (dt.INT, dt.FLOAT, dt.BOOL, dt.STR)],
    )


def test_tuple_get_parity(native):
    _assert_parity(
        native,
        [
            ex.make_tuple(X, Y),
            ex.GetExpression(X, ex.ConstExpression(0), check_if_exists=False),
            ex.GetExpression(
                X, ex.ConstExpression(0),
                default=ex.ConstExpression("dflt"), check_if_exists=True,
            ),
        ],
    )


def test_unwrap_fill_error_parity(native):
    _assert_parity(
        native,
        [
            ex.unwrap(X),
            ex.fill_error(ex.BinaryExpression("/", X, Y), ex.ConstExpression(-1)),
            ex.fill_error(X, Y),
        ],
    )


def test_pointer_parity(native):
    _assert_parity(
        native,
        [
            ex.PointerExpression(_T, X, Y),
            ex.PointerExpression(_T, X, optional=True),
        ],
    )


def test_declare_type_and_const(native):
    import pathway_tpu.internals.dtype as dt

    _assert_parity(
        native,
        [ex.declare_type(dt.ANY, X), ex.ConstExpression("k")],
    )


def test_mixed_native_and_pycall(native):
    """A UDF apply rides CALL_PY inside an otherwise-native program."""
    _assert_parity(
        native,
        [
            ex.BinaryExpression(
                "+",
                ex.apply_with_type(lambda v: (v, v), object, X),
                ex.MakeTupleExpression(Y),
            ),
        ],
        expect_native=True,
    )


def test_raising_udf_contains_row(native):
    """ApplyExpression's closure contains its own exception (error-logged,
    returns ERROR) — the VM must propagate that ERROR through native ops.
    A closure that raises PAST the containment (bare pyfunc) must instead
    trigger the row-level on_error + (ERROR,) path like rowwise_map."""

    def boom(v):
        raise RuntimeError("boom")

    # (a) apply: contained inside the closure -> column is ERROR, no
    # row-level on_error
    e = ex.apply_with_type(boom, int, X)
    prog = expr_vm.lower_programs(
        [ex.BinaryExpression("+", e, ex.ConstExpression(1))], LAYOUT
    )
    assert prog is not None
    logged: list = []
    out = native.vm_eval_batch(
        _batch()[:3], prog, Update, api.ERROR, logged.append
    )
    assert all(u.values == (api.ERROR,) for u in out)
    assert logged == []

    # (b) a raw raising pyfunc (no Apply containment): row-level
    # containment fires exactly like rowwise_map
    raw = native.vm_compile(
        [expr_vm.OP_CALL_PY, 0], (), (lambda kv: (_ for _ in ()).throw(RuntimeError("x")),)
    )
    out2 = native.vm_eval_batch(
        _batch()[:2], (raw,), Update, api.ERROR, logged.append
    )
    assert all(u.values == (api.ERROR,) for u in out2)
    assert len(logged) == 2 and all(isinstance(x, RuntimeError) for x in logged)


def test_json_get_convert_parity(native):
    rows = [
        (Json({"a": 1, "b": [10, 20]}), "a", 1),
        (Json({"a": "s"}), "a", 0),
        (Json({"a": None}), "a", 0),
        (Json(3.5), "q", 0),
        (Json(True), "q", 0),
        (None, "a", 0),
        (E, "a", 0),
    ]
    batch = [Update(_key(i), r, 1) for i, r in enumerate(rows)]
    import pathway_tpu.internals.dtype as dt

    exprs = [
        ex.GetExpression(X, Y, check_if_exists=False),
        ex.GetExpression(
            X, Y, default=ex.ConstExpression(None), check_if_exists=True
        ),
        ex.ConvertExpression(
            dt.INT,
            ex.GetExpression(
                X, ex.ConstExpression("a"),
                default=ex.ConstExpression(None), check_if_exists=True,
            ),
        ),
        ex.ConvertExpression(dt.FLOAT, X),
        ex.ConvertExpression(dt.BOOL, X, unwrap=True),
    ]
    progs = expr_vm.lower_programs(exprs, LAYOUT)
    assert progs is not None
    out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda e: None)
    closures = [e._compile(LAYOUT.resolver) for e in exprs]
    for u_in, u_out in zip(batch, out):
        expected = [c((u_in.key, u_in.values)) for c in closures]
        assert [_canon(g) for g in u_out.values] == [
            _canon(e) for e in expected
        ], (u_in.values, list(u_out.values), expected)


def test_filter_parity(native):
    preds = [
        ex.BinaryExpression(">", X, Y),
        X.is_none(),
        ex.BinaryExpression("==", X, X),
        ex.BinaryExpression("/", ex.ConstExpression(1), X),  # 1/x truthiness
    ]
    for pred in preds:
        prog = expr_vm.lower_program(pred, LAYOUT)
        assert prog is not None
        out = native.vm_filter_batch(_batch(), prog, api.ERROR)
        c = pred._compile(LAYOUT.resolver)
        expected = []
        for u in _batch():
            try:
                keep = c((u.key, u.values))
            except Exception:
                continue
            if keep is not None and keep is not api.ERROR and bool(keep):
                expected.append(u)
        assert [u.key for u in out] == [u.key for u in expected], pred


def test_vm_rejects_malformed_programs(native):
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_JUMP, 999], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_LOAD_CONST, 5], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_CALL_PY, 0], (), ())
    with pytest.raises(ValueError):
        native.vm_compile([99], (), ())
    # stack discipline: underflow and wrong exit depth must be rejected
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_BIN, 0], (), ())  # pops empty stack
    with pytest.raises(ValueError):
        native.vm_compile([expr_vm.OP_POP], (), ())
    with pytest.raises(ValueError):
        native.vm_compile(
            [expr_vm.OP_LOAD_KEY, expr_vm.OP_LOAD_KEY], (), ()
        )  # exits with depth 2
    with pytest.raises(ValueError):
        native.vm_compile(
            [expr_vm.OP_LOAD_KEY, expr_vm.OP_MAKE_TUPLE, 2], (), ()
        )  # MAKE_TUPLE deeper than stack
    with pytest.raises(ValueError):
        # jump into the middle of an instruction's operands
        native.vm_compile([expr_vm.OP_JUMP, 3, expr_vm.OP_LOAD_COL, 0], (), ())


def test_end_to_end_pipeline_matches_disable_native():
    """The same select/filter pipeline prints identically with the VM and
    with PATHWAY_DISABLE_NATIVE=1 (subprocess)."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_markdown('''\n"
        "a | b | s\n"
        "1 | 2 | x\n"
        "3 | 0 | y\n"
        "5 | 4 | z\n"
        "''')\n"
        "out = t.select(t.a, q=t.a * 2 + t.b, d=t.a / t.b,\n"
        "    w=pw.if_else(t.a > 2, t.s, pw.coalesce(t.s, 'n')),\n"
        "    p=t.pointer_from(t.a), m=pw.make_tuple(t.a, t.b)[1])\n"
        "out = out.filter(out.q > 3)\n"
        "pw.debug.compute_and_print(out, include_id=False)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    a = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    env2 = dict(env, PATHWAY_DISABLE_NATIVE="1")
    b = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env2
    )
    assert a.returncode == 0, a.stderr
    assert b.returncode == 0, b.stderr
    assert a.stdout == b.stdout
