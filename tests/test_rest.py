"""REST connector end-to-end: HTTP request -> engine -> response."""

import json
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_rest_connector_roundtrip():
    port = _free_port()

    class QuerySchema(pw.Schema):
        query: str

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema, delete_completed_queries=False
    )
    responses = queries.select(result=pw.apply(lambda q: q.upper(), pw.this.query))
    response_writer(responses)

    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"query": "hello"}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            break
        except (ConnectionError, urllib.error.URLError):
            time.sleep(0.2)  # server still coming up
    assert body == "HELLO"

    # second request exercises the steady-state path
    req2 = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"query": "again"}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req2, timeout=10) as resp:
        assert json.loads(resp.read()) == "AGAIN"

    sched.stop()
    run_t.join(timeout=2)


def test_serve_callable_roundtrip():
    """BaseRestServer.serve_callable registers an async Python function as
    an endpoint via the AsyncTransformer (reference servers.py:227-272):
    REST round-trip, schema inferred from the function signature."""
    from pathway_tpu.xpacks.llm.servers import BaseRestServer

    port = _free_port()
    server = BaseRestServer("127.0.0.1", port)

    @server.serve_callable("/v1/combine")
    async def combine(a, b):
        return {"sum": a + b, "echo": [a, b]}

    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/combine",
        data=json.dumps({"a": 2, "b": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            break
        except (ConnectionError, urllib.error.URLError):
            time.sleep(0.2)
    assert body == {"sum": 5, "echo": [2, 3]}, body

    # sync callables are coerced to async transparently
    sched.stop()
    run_t.join(timeout=2)


def test_serve_callable_sync_fn_and_explicit_schema():
    from pathway_tpu.xpacks.llm.servers import BaseRestServer

    port = _free_port()
    server = BaseRestServer("127.0.0.1", port)

    class S(pw.Schema):
        text: str

    server.serve_callable("/v1/upper", S, lambda text: text.upper())

    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/upper",
        data=json.dumps({"text": "hi there"}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            break
        except (ConnectionError, urllib.error.URLError):
            time.sleep(0.2)
    assert body == "HI THERE"
    sched.stop()
    run_t.join(timeout=2)
